"""Predictor: the public inference endpoint with top-N ensembling.

Parity target: the reference's predictor service (SURVEY.md §2 "Predictor",
§3.3): ``POST /predict`` assigns each request a query id, scatters it onto
every inference worker's queue, gathers the replicas' predictions with a
timeout, and ensembles — probability averaging for classification vectors,
majority vote otherwise. Partial gathers still answer (latency/accuracy
trade-off, SURVEY.md §3.3 note): whatever arrived by the deadline is
ensembled; zero arrivals is a 504.
"""

from __future__ import annotations

import collections
import math
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import (MetricsRegistry, StatsMap, TraceBuffer,
                   mint_trace_id, mount_obs_routes, sanitize_trace_id)
from ..utils.http import STREAM_BUDGET_S, JsonHttpService, StreamResponse
from .breaker import OPEN, BreakerBoard
from .queues import (EXPIRY_SKEW_TOLERANCE_S, QueueHub, pack_message,
                     unpack_message)
from .router import Router
from .slo import BrownoutController, normalize_slo


def nearest_rank(sorted_vals: Sequence[float], p: float) -> float:
    """Nearest-rank quantile over pre-sorted values: ``ceil(p·n)-1``,
    so p95 of 20 samples is the 19th-smallest, not the max. Shared by
    the /health percentiles and the adaptive-gather controller (they
    must agree). Empty input → 0.0."""
    if not sorted_vals:
        return 0.0
    n = len(sorted_vals)
    return sorted_vals[max(0, min(n - 1, math.ceil(p * n) - 1))]


def ensemble_predictions(per_worker: List[List[Any]]) -> List[Any]:
    """Combine replicas' per-query predictions.

    Numeric same-length vectors (class probabilities) are averaged;
    anything else falls back to majority vote (ties → first seen).
    """
    if not per_worker:
        return []
    n_queries = len(per_worker[0])
    out: List[Any] = []
    for q in range(n_queries):
        votes = [w[q] for w in per_worker if q < len(w) and w[q] is not None]
        if not votes:
            out.append(None)
            continue
        try:
            arrs = [np.asarray(v, dtype=np.float64) for v in votes]
            if all(a.shape == arrs[0].shape and a.ndim >= 1 for a in arrs):
                out.append(np.mean(arrs, axis=0).tolist())
                continue
        except (TypeError, ValueError):
            pass
        keys = [repr(v) for v in votes]
        best = max(set(keys), key=lambda k: (keys.count(k), -keys.index(k)))
        out.append(votes[keys.index(best)])
    return out


class Predictor:
    """Scatter/gather over inference workers + ensemble."""

    #: bounded reservoir of recent request latencies; big enough for
    #: stable p50/p95/p99, small enough to sort on every stats() call
    LATENCY_WINDOW = 2048
    #: default whole-stream deadline for predict_stream — generations
    #: run for minutes; gather_timeout is a unary-RPC bound. Shared
    #: with the client SDK via utils.http (it sizes per-event socket
    #: timeouts to this budget).
    STREAM_TIMEOUT = STREAM_BUDGET_S

    #: default fleet queue-backlog caps per best-effort class: beyond
    #: these the shed gate 503s the class with a structured
    #: ``retry_after_s`` instead of letting it deepen the overload.
    #: Interactive is never depth-shed (its protection is admission
    #: priority + preemption, not refusal).
    DEFAULT_SHED_DEPTHS = {"batch": 64, "background": 16}

    #: a gather miss only counts toward a worker's circuit breaker when
    #: the budget it missed was at least this long: misses under an
    #: aggressively learned adaptive budget (or a tiny explicit client
    #: timeout) mean "slower than the controller wants", not "dead" —
    #: shedding those is the adaptive controller's job, and letting
    #: them trip breakers would turn a fleet-wide slowdown into a
    #: fast-fail outage
    BREAKER_MIN_TIMEOUT_S = 1.0

    def __init__(self, hub: QueueHub, worker_ids: Sequence[str],
                 gather_timeout: float = 10.0,
                 adaptive_gather: bool = False,
                 target_answer_frac: float = 0.95,
                 gather_margin: float = 1.5,
                 min_gather_timeout: float = 0.05,
                 breaker_fail_threshold: int = 3,
                 breaker_cooldown_s: float = 2.0,
                 stream_silence_timeout_s: float = 30.0,
                 max_stream_failovers: int = 2,
                 pool_id: str = "",
                 affinity_prefix_chars: int = Router.DEFAULT_PREFIX_CHARS,
                 default_slo: str = "",
                 slo_shed_depths: Optional[Dict[str, int]] = None,
                 brownout_target_p95_s: float = 0.0,
                 brownout_clamp_max_new: int = 16,
                 kv_ship_min_tokens: int = 4) -> None:
        """``adaptive_gather`` enables the serving latency/accuracy
        controller (the reference paper's batching/wait tradeoff,
        SURVEY.md §3.3 note): instead of always waiting
        ``gather_timeout`` for stragglers, the gather deadline tracks
        the observed per-reply latency distribution — the
        ``target_answer_frac`` quantile times ``gather_margin``,
        clamped to [``min_gather_timeout``, ``gather_timeout``]. A
        persistently slow replica stops taxing every request's p50
        (its answers are dropped from the ensemble: slightly less
        accuracy, much less latency), while a healthy fleet keeps full
        ensembles because the quantile tracks its real speed. Explicit
        per-request ``timeout`` always wins.

        **SLO / overload controls**: ``default_slo`` classes requests
        that carry no ``slo`` of their own; ``slo_shed_depths`` caps
        the fleet queue backlog per best-effort class (batch /
        background — interactive is never depth-shed), beyond which
        requests get a structured shed 503 with ``retry_after_s``
        BEFORE they deepen the overload; ``brownout_target_p95_s``
        (> 0 enables the ladder) is the interactive-TTFT-p95 target
        the hysteresis brownout ladder defends — stage 1 halves the
        best-effort caps, stage 2 additionally clamps background
        ``max_new`` to ``brownout_clamp_max_new``, stage 3 pauses
        background entirely. See docs/operations.md "Overload &
        brownout".

        ``kv_ship_min_tokens`` gates the disaggregated prefill leg:
        prompts shorter than this many whitespace tokens prefill
        locally on the decode worker (a short prefill costs less than
        the shipment wait + page install it would replace); longer
        prompts route through a prefill-role worker when the pool has
        one. See docs/operations.md "Disaggregated serving"."""
        self.hub = hub
        self.worker_ids = list(worker_ids)
        self.gather_timeout = gather_timeout
        #: per-worker circuit breakers: fed by gather answer/miss
        #: outcomes, the monotonic staleness signal, and drain
        #: announcements; consulted at every scatter (open workers are
        #: skipped, shrinking the gather quorum; all-open fast-fails)
        self.breakers = BreakerBoard(
            self.worker_ids, fail_threshold=breaker_fail_threshold,
            cooldown_s=breaker_cooldown_s)
        #: single-worker stream placement: prefix-affinity (HRW) with
        #: load-aware fallback over the live pool, breaker-gated —
        #: replaces the old round-robin cursor even for one worker
        self.router = Router(self.worker_ids, self.breakers,
                             prefix_chars=affinity_prefix_chars)
        #: hub key this job's pool membership is published under (the
        #: inference job id); empty = static membership (direct
        #: add_worker/remove_worker calls only)
        self.pool_id = str(pool_id or "")
        self._pool_version = 0.0
        self._last_pool_refresh = 0.0
        self._last_load_refresh = 0.0
        #: mid-stream reply-silence watchdog: no delta/final from the
        #: stream's worker for this long triggers failover to a healthy
        #: replica (NOT the whole-stream timeout — a dead worker must
        #: not cost the client the full stream budget). Generous by
        #: default: a long prefill queued behind busy slots is silence
        self.stream_silence_timeout_s = float(stream_silence_timeout_s)
        self.max_stream_failovers = max(0, int(max_stream_failovers))
        self.kv_ship_min_tokens = max(0, int(kv_ship_min_tokens))
        #: SLO plane: per-job default class, best-effort shed caps,
        #: and the brownout ladder fed by the live interactive p95
        #: (workers publish slo_interactive_ttft_p95_s; the ladder
        #: steps on the fleet max so one hot replica counts)
        self.default_slo = normalize_slo(default_slo)
        self.shed_depths = dict(self.DEFAULT_SHED_DEPTHS)
        for k, v in (slo_shed_depths or {}).items():
            self.shed_depths[normalize_slo(k)] = max(0, int(v))
        self.brownout = BrownoutController(
            target_p95_s=brownout_target_p95_s)
        self.brownout_clamp_max_new = max(1,
                                          int(brownout_clamp_max_new))
        self.adaptive_gather = bool(adaptive_gather)
        self.target_answer_frac = min(1.0, max(0.0, target_answer_frac))
        self.gather_margin = max(1.0, gather_margin)
        self.min_gather_timeout = max(0.0, min_gather_timeout)
        #: observed scatter→reply latencies per ANSWER (not request):
        #: the controller's signal
        self._reply_lat: "collections.deque[float]" = collections.deque(
            maxlen=self.LATENCY_WINDOW)
        #: the obs plane: request counters + fixed-bucket latency
        #: histograms (scraped via /metrics) and the per-request trace
        #: ring (/debug/requests). The bounded reservoir below stays —
        #: it feeds the adaptive-gather CONTROLLER, which wants exact
        #: recent samples, not bucket counts.
        self.metrics = MetricsRegistry()
        self.traces = TraceBuffer(512)
        self._c_requests = self.metrics.counter(
            "requests_served", "predict/predict_stream calls answered")
        self._c_queries = self.metrics.counter(
            "queries_served", "individual queries answered")
        self._h_e2e = self.metrics.histogram(
            "request_seconds", "end-to-end request latency (seconds)")
        self._h_reply = self.metrics.histogram(
            "gather_reply_seconds",
            "scatter-to-reply latency per worker answer (seconds)")
        self.metrics.gauge(
            "gather_deadline_s",
            "adaptive-gather controller's live budget (seconds)",
            fn=self._gather_deadline_s)
        # fault-tolerance plane: breaker trips/recoveries (board
        # counters), open-worker gauge, fast-fail + failover counters
        self.metrics.register_stats(self.breakers.counters)
        self.metrics.gauge(
            "breaker_open_workers",
            "workers currently excluded from scatter "
            "(open/half-open/draining)", fn=self.breakers.n_open)
        self._c_fast_fail = self.metrics.counter(
            "requests_fast_failed",
            "requests 503'd with every breaker open")
        # data-plane survival: the shared kv-client reconnect counters
        # (hub_reconnects_total / hub_rpc_retries_total) plus a down
        # flag — set when a hub op exhausts its reconnect window,
        # cleared by the next op that reaches the kvd. Drives /health,
        # /metrics, and the dashboard's data-plane banner.
        from ..native.client import CLIENT_STATS as _kv_client_stats

        self.metrics.register_stats(_kv_client_stats)
        self._c_dp_failures = self.metrics.counter(
            "data_plane_failures",
            "requests failed with the kvd unreachable past the "
            "reconnect window (structured 503 / resumable event)")
        self._dp_down_at: Optional[float] = None
        self.metrics.gauge(
            "data_plane_down",
            "1 while the last hub op found the kvd unreachable "
            "(predictor fast-fails 503 until it returns)",
            fn=lambda: 0 if self._dp_down_at is None else 1)
        self._c_failover = self.metrics.counter(
            "stream_failovers",
            "mid-stream failovers to another worker")
        self._c_resumable = self.metrics.counter(
            "stream_resumable_errors",
            "streams ended with a resumable error event")
        # SLO plane: shed decisions per class + the live brownout stage
        self._shed_counts = StatsMap({"requests_shed_batch": 0,
                                      "requests_shed_background": 0})
        self.metrics.register_stats(self._shed_counts)
        self._c_shed = self.metrics.counter(
            "requests_shed",
            "best-effort requests 503'd by the SLO shed gate "
            "(structured retry_after_s — backpressure, not failure)")
        self.metrics.gauge(
            "brownout_stage",
            "live brownout ladder stage (0 normal, 1 capped, "
            "2 clamped, 3 background paused)",
            fn=lambda: self.brownout.stage)
        # scale-out plane: router decision counters + live pool gauges
        self.metrics.register_stats(self.router.counters)
        self.metrics.gauge(
            "router_pool_size",
            "workers in this job's routing pool (live membership)",
            fn=lambda: len(self.router))
        self.metrics.gauge(
            "router_affinity_hit_rate",
            "fraction of keyed placements that landed on their HRW "
            "owner (prefix-cache hit proxy)",
            fn=self.router.affinity_hit_rate)
        self._latencies: "collections.deque[float]" = collections.deque(
            maxlen=self.LATENCY_WINDOW)
        #: per-worker publish watermarks for staleness detection:
        #: worker_id -> (last seen uptime_s, local monotonic at change).
        #: Monotonic on BOTH sides — wall-clock steps can't grey out a
        #: healthy fleet (the published_at failure mode)
        self._worker_seen: Dict[str, Tuple[float, float]] = {}
        #: consecutive zero-answer adaptive gathers — drives the
        #: escalating recovery below (a single penalty sample per miss
        #: needs ~0.05·WINDOW misses to move the p95 past a window of
        #: stale fast samples; a fleet-wide slowdown must relearn in a
        #: few requests, not ~100)
        self._gather_misses = 0
        self._last_drain_refresh = 0.0
        self._lock = threading.Lock()

    #: floor between drain-exclusion refreshes on the scatter path —
    #: per-request hub reads would tax the healthy hot path for a
    #: condition that only exists around rolling restarts
    DRAIN_REFRESH_EVERY_S = 1.0

    def _refresh_excluded_workers(self, force: bool = False) -> None:
        """Re-read hub stats for workers the board currently excludes
        as draining. The draining flag is normally cleared when a
        /health render annotates the respawned worker's fresh stats —
        but a predictor used purely through predict()/predict_stream
        never renders /health, and without this re-check a rolling
        restart would leave drained-then-respawned workers excluded
        forever (a shrunken quorum while siblings stay healthy, a
        permanent fast-fail with none). Rate-limited unless ``force``
        (the about-to-fast-fail path, where one extra hub read beats a
        wrong 503)."""
        now = time.monotonic()
        if not force and now - self._last_drain_refresh < \
                self.DRAIN_REFRESH_EVERY_S:
            return
        # lock-free rate-limiter stamp: threads racing the
        # check-then-set at worst both refresh (one redundant hub
        # read), never corrupt state
        self._last_drain_refresh = now  # rafiki: noqa[shared-state-race]
        for wid, st in self.breakers.snapshot().items():
            if not st.get("draining"):
                continue
            try:
                s = self.hub.get_worker_stats(wid)
            except Exception:  # rafiki: noqa[silent-except] — a hub
                continue       # hiccup just delays the re-admission
            if s is not None:
                self._annotate_staleness(wid, s)

    #: floor between hub membership reads on the request path — a
    #: scale event lands within this; per-request reads would tax every
    #: request for a change that happens a few times an hour
    POOL_REFRESH_EVERY_S = 2.0
    #: floor between load-signal refreshes feeding the router (worker
    #: stats + queue depths); workers republish at a similar cadence
    LOAD_REFRESH_EVERY_S = 1.0

    # ---- dynamic pool membership (scale-out) ----
    def add_worker(self, wid: str) -> None:
        """Admit a new pool member live: breaker (CLOSED) first so the
        id is scatter-eligible the instant the router can pick it, then
        the router table (HRW claims only the keys it now owns)."""
        with self._lock:
            if wid in self.worker_ids:
                return
            # membership mutations all hold _lock; the lock-free
            # readers are single GIL-atomic len()/list() snapshots in
            # advisory payload fields, where one-refresh staleness is
            # part of the contract (see _refresh_membership)
            self.worker_ids.append(wid)  # rafiki: noqa[shared-state-race]
        self.breakers.add_worker(wid)
        self.router.add_worker(wid)

    def remove_worker(self, wid: str) -> None:
        """Remove a departed member: breaker state goes first (unary
        scatter stops immediately, and a straggling gather outcome
        can't resurrect the id), then the router table (streams stop
        placing there; HRW remaps only this worker's keys), then the
        staleness watermark. An in-flight stream on the removed worker
        notices on its next loop tick and fails over with its
        delivered text as the forced prefix — removal is never a
        dropped stream."""
        self.breakers.remove_worker(wid)
        self.router.remove_worker(wid)
        with self._lock:
            if wid in self.worker_ids:
                self.worker_ids.remove(wid)
            self._worker_seen.pop(wid, None)

    def _refresh_membership(self, force: bool = False) -> None:
        """Apply the control plane's published pool membership (see
        ``QueueHub.put_pool_members``). Rate-limited; ``force`` on the
        about-to-fail paths. Only newer versions apply, and an empty
        worker list is ignored — a publisher bug must not unroute the
        whole fleet."""
        if not self.pool_id:
            return
        now = time.monotonic()
        if not force and now - self._last_pool_refresh < \
                self.POOL_REFRESH_EVERY_S:
            return
        # lock-free rate-limiter stamp, same contract as
        # _last_drain_refresh above
        self._last_pool_refresh = now  # rafiki: noqa[shared-state-race]
        try:
            pool = self.hub.get_pool_members(self.pool_id)
        except Exception:  # rafiki: noqa[silent-except] — a hub hiccup
            return         # just delays the membership diff
        if not isinstance(pool, dict):
            return
        workers = [str(w) for w in (pool.get("workers") or []) if w]
        if not workers:
            return
        try:
            version = float(pool.get("version") or 0.0)
        except (TypeError, ValueError):
            version = 0.0
        if version and version <= self._pool_version:
            return  # already applied (or an out-of-order straggler)
        # monotone float under max(): two racing refreshers at worst
        # re-apply the same membership diff, which is idempotent
        self._pool_version = max(  # rafiki: noqa[shared-state-race]
            self._pool_version, version)
        with self._lock:
            have = list(self.worker_ids)
        for wid in workers:
            if wid not in have:
                self.add_worker(wid)
        want = set(workers)
        for wid in have:
            if wid not in want:
                self.remove_worker(wid)

    def _refresh_load_signals(self) -> None:
        """Feed the router's load view (and the staleness/drain
        breaker signals — one read serves both) from the hub's
        published worker stats + queue depths. Rate-limited."""
        now = time.monotonic()
        with self._lock:
            # atomic check-then-set: this refresh now TICKS the
            # brownout ladder's dwell counters, and two request
            # threads racing the unguarded watermark would double-tick
            # a transition ("dwell consecutive observations" is the
            # hysteresis contract)
            if now - self._last_load_refresh < \
                    self.LOAD_REFRESH_EVERY_S:
                return
            self._last_load_refresh = now
            members = list(self.worker_ids)
        p95s: List[float] = []
        for wid in members:
            try:
                s = self.hub.get_worker_stats(wid)
                depth = self.hub.query_depth(wid)
            except Exception:  # rafiki: noqa[silent-except] — load
                continue       # signals are advisory; stale beats dead
            if s is not None:
                annotated = self._annotate_staleness(wid, s)
                self.router.observe(wid, s)
                v = s.get("slo_interactive_ttft_p95_s")
                if not annotated.get("stale") and \
                        isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    # a dead/stuck worker's LAST published p95 must
                    # not pin the ladder: stale stats are liveness
                    # fiction, not a latency signal
                    p95s.append(float(v))
            self.router.observe_queue_depth(wid, depth)
        # brownout ladder tick: the fleet MAX interactive p95 (one hot
        # replica is an SLO breach; averaging would hide it). Rides
        # this rate-limited refresh so the ladder's dwell counts are
        # roughly seconds, and an idle/recovered fleet (no samples)
        # walks back down.
        self.brownout.observe(max(p95s) if p95s else None)

    def _gather_deadline_s(self) -> float:
        """The adaptive controller's current gather budget."""
        if not self.adaptive_gather:
            return self.gather_timeout
        with self._lock:
            lat = sorted(self._reply_lat)
            n_workers = len(self.worker_ids)
        if len(lat) < 2 * n_workers:
            return self.gather_timeout  # warmup: no signal yet
        return max(self.min_gather_timeout,
                   min(self.gather_timeout,
                       nearest_rank(lat, self.target_answer_frac)
                       * self.gather_margin))

    # ---- SLO shed gate (predictor-side overload backpressure) ----
    def shed_verdict(self, slo: Optional[str] = None
                     ) -> Optional[Dict[str, Any]]:
        """Should a ``slo``-class request be shed RIGHT NOW? None =
        admit; otherwise the structured shed payload (a 503 at the
        HTTP front). Best-effort classes are refused with a
        ``retry_after_s`` once the fleet queue backlog exceeds their
        (brownout-adjusted) cap — BEFORE they deepen the overload —
        and background is refused outright at brownout stage 3.
        Interactive is never shed here: its protection is engine-side
        priority + preemption, not refusal. Shedding is backpressure,
        not failure — the reply names the class, the live stage, and
        when retrying can help."""
        cls = normalize_slo(slo, default=self.default_slo)
        # refresh BEFORE the interactive early-return: this
        # (rate-limited) call is what ticks the brownout ladder, and
        # a fleet serving only interactive traffic must still walk
        # the ladder back down after an overload ends — de-escalation
        # cannot wait for the next best-effort arrival
        self._refresh_load_signals()
        if cls == "interactive":
            return None
        stage = self.brownout.stage
        cap = self.brownout.shed_cap(cls, self.shed_depths.get(cls, 0))
        # fleet backlog FOR THIS CLASS: unpopped hub messages plus the
        # engines' published class-queue depths (workers pop the hub
        # eagerly, so overload backlog sits in the engine queues)
        depth = (self.router.total_queue_depth()
                 + self.router.class_backlog(cls))
        if cls == "background" and stage >= 3:
            reason = "background paused (brownout stage 3)"
        elif cap >= 0 and depth > cap:
            reason = (f"{cls} backlog {depth} over cap {cap}"
                      + (f" (brownout stage {stage})" if stage else ""))
        else:
            return None
        retry = round(min(30.0, 1.0 + 0.1 * max(0, depth - max(cap, 0))),
                      3)
        self._c_shed.inc()
        self._shed_counts.inc(f"requests_shed_{cls}")
        return {"shed": True, "slo": cls, "error": f"shed: {reason}",
                "retry_after_s": retry, "brownout_stage": stage}

    def _brownout_sampling(self, cls: str,
                           sampling: Optional[Dict]) -> Optional[Dict]:
        """Stage >= 2: clamp background ``max_new`` so long best-effort
        generations release their slots/pages sooner (the 'clamped'
        rung of the ladder). Other classes/stages pass through
        untouched."""
        if cls == "interactive":
            return sampling
        mn = (sampling or {}).get("max_new")
        c = self.brownout.clamp_max_new(cls, mn,
                                        self.brownout_clamp_max_new)
        if c is not None and c != mn:
            sampling = dict(sampling or {})
            sampling["max_new"] = c
        return sampling

    def predict(self, queries: Sequence[Any],
                timeout: Optional[float] = None,
                sampling: Optional[Dict] = None,
                trace_id: Optional[str] = None,
                slo: Optional[str] = None
                ) -> Tuple[List[Any], Dict]:
        """Returns (ensembled predictions, info dict). ``sampling``
        (generation jobs only) rides with the message to the decode
        loop: {temperature, top_k, top_p, seed, eos_id, max_new,
        adapter_id} — seeded draws are reproducible per
        (seed, position) regardless of serving load; max_new is
        clamped by the worker's configured cap.

        ``trace_id``: honored when well-formed (the HTTP front passes
        an inbound ``X-Rafiki-Trace-Id``), else minted here; it rides
        in the scatter payload so worker-side span records join this
        predictor's across ``/debug/requests``, and comes back in
        ``info["trace_id"]``.

        ``slo`` (``interactive``/``batch``/``background``; default =
        the job's ``default_slo``): the request's admission class. It
        rides the scatter payload to the engine's class-aware queue,
        and best-effort classes may be SHED here (structured 503 with
        ``retry_after_s`` via ``info["shed"]``) when the backlog cap
        or brownout ladder says admitting would hurt interactive
        traffic."""
        t0 = time.monotonic()
        cls = normalize_slo(slo, default=self.default_slo)
        tid = sanitize_trace_id(trace_id) or mint_trace_id()
        # the down-gate runs FIRST: everything below (the shed gate's
        # load refresh included) touches the hub, and a known-down
        # plane must cost one 0.25s-bounded probe, not a reconnect
        # window per hub op
        gate = self._data_plane_gate(tid)
        if gate is not None:
            self._c_requests.inc()
            return [], {"workers_answered": 0, "workers_asked": 0,
                        "workers_skipped": len(self.worker_ids),
                        "latency_s": time.monotonic() - t0,
                        "errors": [gate["error"]],
                        "trace_id": tid, **gate}
        shed = self.shed_verdict(cls)
        if shed is not None:
            self._c_requests.inc()
            self.traces.start(tid, request_id="", span="shed",
                              slo=cls,
                              retry_after_s=shed["retry_after_s"])
            return [], {"workers_answered": 0, "workers_asked": 0,
                        "workers_skipped": len(self.worker_ids),
                        "latency_s": time.monotonic() - t0,
                        "errors": [shed["error"]], "fast_fail": True,
                        "trace_id": tid, **shed}
        sampling = self._brownout_sampling(cls, sampling)
        adaptive = timeout is None and self.adaptive_gather
        timeout = self._gather_deadline_s() if timeout is None else timeout
        qid = uuid.uuid4().hex
        self.traces.start(tid, request_id=qid, span="received",
                          n_queries=len(queries),
                          timeout_s=round(float(timeout), 4))
        # live membership first: a scaled pool must be scattered to
        # (and a removed worker not) without a predictor rebuild
        self._refresh_membership()
        # breaker gating: open workers are skipped at scatter time —
        # their share of the gather quorum shrinks accordingly. All
        # open: fast-fail with a structured 503 + retry_after_s instead
        # of burning the whole gather budget on a dead fleet.
        if self.breakers.any_draining():
            # drained workers re-admit themselves through their fresh
            # published stats (rate-limited; a partial fleet must not
            # serve a shrunken quorum forever after a rolling restart)
            self._refresh_excluded_workers()
        targets = self.breakers.targets()
        if not targets:
            self._refresh_membership(force=True)
            self._refresh_excluded_workers(force=True)
            targets = self.breakers.targets()
        if not targets:
            self._c_fast_fail.inc()
            self._c_requests.inc()
            retry = round(self.breakers.retry_after_s(), 3)
            self.traces.add_span(tid, "fast_fail",
                                 retry_after_s=retry)
            return [], {"workers_answered": 0, "workers_asked": 0,
                        "workers_skipped": len(self.worker_ids),
                        "latency_s": time.monotonic() - t0,
                        "errors": ["no worker available "
                                   "(all circuit breakers open)"],
                        "fast_fail": True, "retry_after_s": retry,
                        "trace_id": tid}
        deadline = t0 + timeout
        # the wall-clock deadline rides with the query: a worker that
        # pops it too late drops it instead of computing an answer
        # nobody will read (and recreating a discarded reply queue).
        # ttl_s/sent_ts are the relative twin — workers prefer them,
        # judged against their own skew estimate (see worker._expired)
        payload = {"id": qid, "queries": _stack(queries),
                   "deadline_ts": time.time() + timeout,  # rafiki: noqa[taint-wall-clock-flow] — legacy-worker fallback; ttl_s+sent_ts below is the sanctioned path
                   "ttl_s": float(timeout), "sent_ts": time.time(),
                   "trace_id": tid, "slo": cls}
        if sampling:
            payload["sampling"] = dict(sampling)
        msg = pack_message(payload)
        # condemn the reply queue up front: a worker inside its expiry
        # skew tolerance may answer after our discard below, recreating
        # the queue in the kv store — the pre-armed TTL collects it
        try:
            self.hub.arm_reply_ttl(
                qid, timeout + EXPIRY_SKEW_TOLERANCE_S + 30.0)
        except Exception:  # rafiki: noqa[silent-except] — the
            pass           # TTL is defense-in-depth
        per_worker: List[List[Any]] = []
        errors: List[str] = []
        answered: set = set()
        n_draining = 0
        try:
            for wid in targets:
                self.hub.push_query(wid, msg)
        except ConnectionError as e:
            # the kvd is unreachable past the client's reconnect
            # window: fast-fail with a structured shed-style 503
            # instead of hanging the caller into a gather timeout
            verdict = self._data_plane_lost(tid, e)
            self._c_requests.inc()
            return [], {"workers_answered": 0, "workers_asked": 0,
                        "workers_skipped": len(self.worker_ids),
                        "latency_s": time.monotonic() - t0,
                        "errors": [verdict["error"]],
                        "trace_id": tid, **verdict}
        self.traces.add_span(tid, "scattered", workers=len(targets))
        try:
            for _ in targets:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                reply_bytes = self.hub.pop_prediction(qid, remaining)
                if reply_bytes is None:
                    break
                try:
                    reply = unpack_message(reply_bytes)
                    if not isinstance(reply, dict):
                        raise ValueError("reply is not a mapping")
                except Exception:  # rafiki: noqa[silent-except] — a
                    # corrupted reply (torn write, chaos injection) is
                    # one replica's bad answer, not a request failure:
                    # skip it and keep gathering the others
                    errors.append("undecodable reply payload")
                    continue
                wid_r = str(reply.get("worker_id") or "")
                if wid_r:
                    # any decodable reply — answer OR structured error
                    # — proves the worker is alive and responsive
                    answered.add(wid_r)
                    self.breakers.record_success(wid_r)
                if reply.get("error"):
                    # error replies are NOT controller answers: a
                    # fast-failing replica must not drag the learned
                    # budget down to its ~ms error latency (healthy-
                    # but-slower replicas would get shed while requests
                    # 504 on a 'fully answering' fleet)
                    errors.append(str(reply["error"]))
                    if reply.get("draining") and wid_r:
                        # voluntary drain (rolling restart): stop
                        # scattering to it until its stats say otherwise
                        n_draining += 1
                        self.breakers.set_draining(wid_r, True)
                    continue
                reply_lat = time.monotonic() - t0
                with self._lock:  # controller signal: scatter→ANSWER
                    self._reply_lat.append(reply_lat)
                self._h_reply.observe(reply_lat)
                self.traces.add_span(tid, "reply", worker=wid_r)
                per_worker.append(list(reply["predictions"]))
        except ConnectionError as e:
            # mid-gather data-plane loss (reconnect window exhausted):
            # same structured fast-fail — answers gathered so far are
            # a partial quorum nobody can complete
            verdict = self._data_plane_lost(tid, e)
            self._c_requests.inc()
            return [], {"workers_answered": len(per_worker),
                        "workers_asked": len(targets),
                        "workers_skipped":
                            len(self.worker_ids) - len(targets),
                        "latency_s": time.monotonic() - t0,
                        "errors": errors + [verdict["error"]],
                        "trace_id": tid, **verdict}
        finally:
            # drop the reply queue even on a gather error: late answers
            # must not accumulate in the hub/kv store forever
            try:
                self.hub.discard_prediction_queue(qid)
            except Exception:  # rafiki: noqa[silent-except] —
                pass           # cleanup is best-effort
        self._data_plane_ok()  # the gather reached the kvd: clear the
        #                        down flag (banner + 503 gate)
        latency = time.monotonic() - t0
        self._c_queries.inc(len(queries))
        self._c_requests.inc()
        self._h_e2e.observe(latency)
        with self._lock:
            self._latencies.append(latency)
            if adaptive and not per_worker:
                # anti-death-spiral: a zero-ANSWER gather under the
                # ADAPTIVE budget means the whole fleet got slower (or
                # error-only) under the learned quantile — with no
                # answers recorded the budget would freeze low and
                # every request would 504 forever. Escalate: each
                # consecutive miss doubles the penalty weight (4x the
                # failed budget, capped at the static timeout), and
                # after 3 straight misses the reservoir is flushed —
                # the old latency distribution no longer describes the
                # fleet, and an empty window drops the controller back
                # to warmup (static budget) to relearn from scratch.
                penalty = min(self.gather_timeout,
                              max(timeout, 1e-3) * 4.0)
                if latency < timeout:
                    # the gather ended BEFORE the budget — every worker
                    # error-replied fast, so the fleet is RESPONSIVE (a
                    # bad request, not a slow fleet): keep the budget-
                    # raising penalty sample, but never let a
                    # misbehaving client escalate to the flush and wipe
                    # a healthy learned distribution. A fleet that ran
                    # the budget OUT (even with some fast errors mixed
                    # in) counts as a real miss below.
                    self._reply_lat.append(penalty)
                else:
                    self._gather_misses += 1
                    if self._gather_misses >= 3:
                        self._reply_lat.clear()
                        self._gather_misses = 0
                    else:
                        self._reply_lat.extend(
                            [penalty] * (1 << (self._gather_misses - 1)))
            elif adaptive:
                # only an answer under the ADAPTIVE budget proves the
                # learned budget works again — explicit-timeout traffic
                # answering must not starve the 3-miss flush
                self._gather_misses = 0
        # breaker feed: a scattered-to worker that never replied inside
        # the budget is a miss — but only when the budget was a real
        # liveness test (see BREAKER_MIN_TIMEOUT_S): misses under a
        # collapsed adaptive budget are the controller shedding
        # stragglers, not the fleet dying
        if timeout >= self.BREAKER_MIN_TIMEOUT_S:
            for wid in targets:
                if wid not in answered:
                    self.breakers.record_failure(wid)
        self.traces.add_span(tid, "done", answered=len(per_worker),
                             latency_s=round(latency, 4))
        info = {"workers_answered": len(per_worker),
                "workers_asked": len(targets),
                "workers_skipped": len(self.worker_ids) - len(targets),
                "latency_s": latency, "errors": errors,
                "trace_id": tid}
        if not per_worker and errors and n_draining == len(errors):
            # every reply was a drain rejection (rolling restart caught
            # mid-window): tell the client WHEN retrying helps instead
            # of a bare 504 — the HTTP front maps this to 503
            info["fast_fail"] = True
            info["retry_after_s"] = round(
                max(1.0, self.breakers.retry_after_s()), 3)
        return ensemble_predictions(per_worker), info

    def _pick_stream_worker(self, queries: Optional[Sequence[Any]] = None,
                            exclude=()) -> Optional[str]:
        """Route one stream through the affinity/load router:
        prefix-affinity (HRW over the live pool) with load-aware
        fallback, minus workers this stream already failed on. The
        open/draining gating — including the at-most-ONE half-open
        probe when no closed candidate exists — lives in
        :meth:`Router.select` now. None when no candidate exists (the
        resumable-error path)."""
        self._refresh_membership()
        if self.breakers.any_draining():
            self._refresh_excluded_workers()  # rate-limited
        self._refresh_load_signals()
        key = self.router.affinity_key(queries)
        wid = self.router.select(key, exclude=exclude)
        if wid is None:
            # drained workers re-admit themselves via fresh stats, and
            # a scale event may have landed since the last poll
            self._refresh_membership(force=True)
            self._refresh_excluded_workers(force=True)
            wid = self.router.select(key, exclude=exclude)
        return wid

    #: retry hint handed out with the data-plane-down 503: a supervised
    #: kvd respawn + WAL replay lands within ~1-2s, so the first
    #: honored retry is expected to succeed
    DATA_PLANE_RETRY_S = 2.0

    def _dp_verdict(self) -> Dict[str, Any]:
        """The one data-plane-down 503 payload (gated and mid-request
        paths must not diverge: clients type on ``data_plane_down``)."""
        return {"error": "data plane unreachable (kvd down?) — "
                         "retry after the hint",
                "data_plane_down": True, "fast_fail": True,
                "retry_after_s": self.DATA_PLANE_RETRY_S}

    def _data_plane_lost(self, tid: str, err: Exception
                         ) -> Dict[str, Any]:
        """Record a hub op that exhausted its reconnect window and
        build the structured shed-style verdict: the HTTP front maps
        it to a 503 with ``retry_after_s`` + ``data_plane_down`` so
        clients back off instead of hanging into a gather timeout."""
        import logging

        self._c_dp_failures.inc()
        with self._lock:
            self._dp_down_at = time.monotonic()
        logging.getLogger(__name__).warning(
            "data plane unreachable (%s): fast-failing with "
            "retry_after_s=%.1f", err, self.DATA_PLANE_RETRY_S)
        self.traces.add_span(tid, "data_plane_down",
                             retry_after_s=self.DATA_PLANE_RETRY_S)
        return self._dp_verdict()

    def _data_plane_ok(self) -> None:
        with self._lock:
            self._dp_down_at = None

    def _data_plane_gate(self, tid: str) -> Optional[Dict[str, Any]]:
        """Fast-fail gate for requests arriving while the plane is
        known-down: one cheap TCP liveness probe (0.25s bound; a dead
        port refuses in ~0) decides — up → clear the flag and serve,
        down → an INSTANT structured 503 instead of re-stalling every
        request in the client's reconnect window. None = proceed."""
        with self._lock:
            if self._dp_down_at is None:
                return None
        host = getattr(self.hub, "_host", None)
        port = int(getattr(self.hub, "_port", 0) or 0)
        if not host or port <= 0:
            return None  # socketless hub (in-proc): nothing to gate
        import socket

        try:
            socket.create_connection((host, port), timeout=0.25).close()
        except OSError:
            self._c_dp_failures.inc()
            if tid:  # the HTTP front's SSE pre-flight gates with no
                # trace record yet
                self.traces.add_span(tid, "data_plane_down",
                                     gated=True,
                                     retry_after_s=self.DATA_PLANE_RETRY_S)
            return self._dp_verdict()
        self._data_plane_ok()  # the plane answered: serve normally
        return None

    def data_plane_health(self) -> Dict[str, Any]:
        """The /health ``data_plane`` block (feeds the dashboard
        banner)."""
        with self._lock:
            down_at = self._dp_down_at
        return {"down": down_at is not None,
                "down_for_s": (0.0 if down_at is None
                               else round(time.monotonic() - down_at,
                                          2)),
                "failures": int(self._c_dp_failures.value)}

    def _resumable_final(self, acc: Dict[int, str], n_queries: int,
                         error: str, qid: str, tid: str) -> Dict:
        """The structured terminal event for a stream that could not be
        failed over: the client SDK holds (qid + accumulated text) and
        can auto-resume by re-requesting with ``resume`` once
        ``retry_after_s`` elapses."""
        self._c_resumable.inc()
        return {"done": True, "error": error, "resumable": True,
                "qid": qid, "trace_id": tid,
                "retry_after_s": round(
                    max(0.05, self.breakers.retry_after_s()), 3),
                "partial": [acc.get(i) for i in range(n_queries)]}

    def predict_stream(self, queries: Sequence[Any],
                       timeout: Optional[float] = None,
                       sampling: Optional[Dict] = None,
                       trace_id: Optional[str] = None,
                       resume_partial: Optional[Sequence[Any]] = None,
                       slo: Optional[str] = None):
        """Streaming generation: yield per-query text deltas as the
        decode loop produces them, then a final event.

        Events, in order: zero or more ``{"delta": {qi: text}}`` (append
        ``text`` to query ``qi``'s output), at most one ``{"replace":
        {qi: text}}`` (the authoritative final text diverged from the
        streamed prefix — replace, don't append), then exactly one of
        ``{"done": True, "predictions": [...], "info"}`` or ``{"done":
        True, "error": ...}``. Every stream ends with a done event,
        including on hub failures mid-stream. Unlike :meth:`predict`,
        the request goes to ONE worker, placed by the affinity/load
        :class:`Router` (shared prefixes colocate on the worker holding
        their KV snapshot, ties break to the least-loaded replica): an
        ensemble over replicas has no meaningful token stream —
        mid-generation the replicas disagree, and averaging text deltas
        is nonsense. The
        reference has no streaming path at all (SURVEY.md §3.3 is
        strictly request/response); this is the continuous-batching
        engine's ``poll_partial`` surfaced end to end.

        ``timeout`` bounds the WHOLE stream; default
        ``STREAM_TIMEOUT`` (not ``gather_timeout``, which is sized for
        unary request/response — a generation legitimately runs for
        minutes).

        **Failover**: a dead/stale worker mid-stream (circuit-breaker
        trip or ``stream_silence_timeout_s`` of reply silence — never
        the whole-stream timeout) re-submits the request to a healthy
        worker with the already-emitted text as a forced prefix; the
        engine re-ingests it through chunked prefill and the stream
        resumes without duplicating or losing text. When no healthy
        worker exists the terminal event is a structured *resumable*
        error (``resumable`` + ``qid`` + ``partial`` +
        ``retry_after_s``) the client SDK can auto-resume via
        ``resume_partial`` — which is also the server side of a
        client-driven resume.

        ``slo``: admission class (see :meth:`predict`); a shed
        best-effort stream ends with a single
        ``{"done": True, "shed": True, "retry_after_s": ...}`` event
        (the HTTP front pre-flights the same verdict into a 503
        before the SSE response commits)."""
        t0 = time.monotonic()
        cls = normalize_slo(slo, default=self.default_slo)
        tid = sanitize_trace_id(trace_id) or mint_trace_id()
        # accumulated text per query index — the final predictions
        # message may carry tokens never sent as deltas (the request
        # finished mid-fused-step); the tail is emitted before "done".
        # A client resume seeds it with the partial text the previous
        # stream delivered (the failover machinery re-used end to end).
        acc: Dict[int, str] = {}
        if resume_partial:
            for i, p in enumerate(list(resume_partial)[:len(queries)]):
                if isinstance(p, str) and p:
                    acc[i] = p
        # the down-gate runs FIRST (the shed gate's load refresh
        # touches the hub): a known-down plane costs one 0.25s-bounded
        # probe, then an instant RESUMABLE terminal event carrying any
        # resume seed — the SDK honors retry_after_s and re-opens
        # against the respawned kvd
        gate = self._data_plane_gate(tid)
        if gate is not None:
            self._c_resumable.inc()
            yield {"done": True, "resumable": True, "qid": "",
                   "trace_id": tid,
                   "partial": [acc.get(i)
                               for i in range(len(queries))],
                   **gate}
            return
        shed = self.shed_verdict(cls)
        if shed is not None:
            yield {"done": True, **shed}
            return
        sampling = self._brownout_sampling(cls, sampling)
        timeout = self.STREAM_TIMEOUT if timeout is None else timeout
        deadline = t0 + timeout
        self.traces.start(tid, request_id="", span="received",
                          n_queries=len(queries), stream=True,
                          resumed=bool(acc))
        final: Optional[Dict[str, Any]] = None
        qid = ""
        tried: set = set()
        attempts = 0
        try:
            while final is None:  # one iteration per scatter attempt
                if attempts > self.max_stream_failovers:
                    final = self._resumable_final(
                        acc, len(queries),
                        "stream failover limit reached", qid, tid)
                    break
                wid = self._pick_stream_worker(queries, tried)
                if wid is None:
                    final = self._resumable_final(
                        acc, len(queries),
                        "no healthy worker available", qid, tid)
                    break
                if qid:  # leaving a previous attempt's reply queue
                    try:
                        self.hub.discard_prediction_queue(qid)
                    except Exception:  # rafiki: noqa[silent-except] —
                        pass           # cleanup is best-effort
                attempts += 1
                qid = uuid.uuid4().hex
                remaining = deadline - time.monotonic()
                payload = {"id": qid, "queries": _stack(queries),
                           "stream": True,
                           "deadline_ts": time.time() + remaining,  # rafiki: noqa[taint-wall-clock-flow] — legacy-worker fallback; ttl_s+sent_ts is the sanctioned path
                           "ttl_s": float(remaining),
                           "sent_ts": time.time(), "trace_id": tid,
                           "slo": cls}
                if sampling:
                    payload["sampling"] = dict(sampling)
                fp = {str(i): t for i, t in acc.items() if t}
                if fp:
                    # the failover worker re-ingests the delivered text
                    # as a forced prompt prefix and continues the
                    # stream past it (TextDecodeEngine.submit)
                    payload["forced_prefix"] = fp
                elif all(isinstance(q, str) for q in queries) and \
                        any(len(q.split()) >= self.kv_ship_min_tokens
                            for q in queries):
                    # disaggregated prefill/decode: when the pool has a
                    # prefill-role worker, ship the prompt there FIRST
                    # (it chews chunked prefill and forwards the KV
                    # pages to `wid` over the hub) and mark the decode
                    # leg so `wid` holds admission briefly for the
                    # shipment — the decode worker's active streams
                    # never interleave with this prompt's prefill.
                    # Skipped on failover resumes (the forced prefix
                    # re-ingest covers a longer prompt than any
                    # shipment) and for non-text queries (no prompt to
                    # prefill). Every failure mode — prefill worker
                    # dead, shipment lost/late/mismatched — degrades
                    # to the decode worker's local re-prefill.
                    pw = self.router.select_prefill(exclude=tried)
                    if pw is not None:
                        payload["kv_from"] = pw
                        pre = {k: v for k, v in payload.items()
                               if k not in ("stream", "kv_from")}
                        pre["prefill_for"] = wid
                        try:
                            self.hub.push_query(pw, pack_message(pre))
                        except Exception:  # noqa: BLE001 — the leg is
                            # best-effort: a hub error here must not
                            # fail the request (the decode push below
                            # hasn't happened yet). Drop kv_from so
                            # the decode worker prefills immediately
                            # instead of waiting out kv_wait_s for a
                            # shipment that was never dispatched.
                            payload.pop("kv_from", None)
                            import logging

                            logging.getLogger(__name__).warning(
                                "prefill leg push to %s failed; "
                                "decode worker prefills locally", pw,
                                exc_info=True)
                        else:
                            self.traces.add_span(tid, "prefill_leg",
                                                 worker=pw, decode=wid)
                try:
                    self.hub.arm_reply_ttl(
                        qid, remaining + EXPIRY_SKEW_TOLERANCE_S + 30.0)
                except Exception:  # rafiki: noqa[silent-except] —
                    pass           # the TTL is defense-in-depth
                self.hub.push_query(wid, pack_message(payload))
                self.traces.add_span(
                    tid, "scattered" if attempts == 1 else "failover",
                    worker=wid, request_id=qid)
                last_event = time.monotonic()
                failover_reason = ""
                saw_event = False  # any reply bytes from this worker
                while True:  # one attempt's event loop
                    now = time.monotonic()
                    remaining = deadline - now
                    if remaining <= 0:
                        final = {"done": True,
                                 "error": "stream timed out",
                                 "partial": [acc.get(i) for i in
                                             range(len(queries))]}
                        break
                    silence_left = (last_event
                                    + self.stream_silence_timeout_s
                                    - now)
                    if silence_left <= 0:
                        failover_reason = "reply silence"
                        break
                    if self.breakers.state(wid) == OPEN:
                        # concurrent traffic (or the staleness feed)
                        # already declared this worker dead — don't
                        # wait out our own silence window
                        failover_reason = "breaker open"
                        break
                    if wid not in self.router:
                        # the pool scaled this worker out mid-stream
                        # (remove_worker / membership diff): fail over
                        # now with the delivered text as the forced
                        # prefix instead of riding a departing worker
                        failover_reason = "worker removed"
                        break
                    # bounded pop: wake at least once per second so a
                    # breaker trip is noticed promptly even while the
                    # silence budget is long
                    reply_bytes = self.hub.pop_prediction(
                        qid, min(remaining, silence_left, 1.0))
                    # the pop RETURNED (bytes or a clean timeout):
                    # the hub is reachable — clear the down flag the
                    # unary path clears at gather end (streams may be
                    # the only traffic)
                    self._data_plane_ok()
                    if reply_bytes is None:
                        continue  # re-check timeout/silence/breaker
                    saw_event = True
                    try:
                        reply = unpack_message(reply_bytes)
                        if not isinstance(reply, dict):
                            raise ValueError("reply is not a mapping")
                    except Exception:  # rafiki: noqa[silent-except]
                        # — a corrupted payload from this worker is a
                        # failover trigger, not a dead stream
                        failover_reason = "undecodable reply"
                        break
                    if reply.get("error"):
                        if reply.get("draining"):
                            # voluntary drain rejection: route the
                            # stream elsewhere, no breaker penalty
                            self.breakers.set_draining(wid, True)
                            failover_reason = "worker draining"
                            break
                        if reply.get("expired"):
                            # the worker popped the query past its
                            # deadline and said so (structured, not a
                            # silent drop): fail over NOW — the
                            # remaining stream budget goes to a
                            # replica that can still answer, instead
                            # of waiting out the silence window
                            failover_reason = "expired at worker"
                            break
                        # same terminal contract as the timeout branch:
                        # the client learns what text is authoritative
                        final = {"done": True,
                                 "error": str(reply["error"]),
                                 "partial": [acc.get(i) for i in
                                             range(len(queries))]}
                        break
                    last_event = time.monotonic()
                    self.breakers.record_success(wid)
                    if "delta" in reply:
                        d = {int(k): str(v)
                             for k, v in dict(reply["delta"]).items()}
                        if not acc:  # first streamed token(s)
                            self.traces.add_span(tid, "first_delta")
                        for k, v in d.items():
                            acc[k] = acc.get(k, "") + v
                        yield {"delta": {str(k): v
                                         for k, v in d.items()}}
                        continue
                    preds = list(reply.get("predictions") or [])
                    tail: Dict[str, str] = {}
                    replace: Dict[str, str] = {}
                    for qi, full in enumerate(preds):
                        sent = acc.get(qi, "")
                        if not isinstance(full, str) or full == sent:
                            continue
                        if full.startswith(sent):
                            tail[str(qi)] = full[len(sent):]
                        else:  # streamed prefix diverged (shouldn't
                            # happen with append-only poll_partial;
                            # authoritative text wins, flagged as
                            # replace — NOT a delta a concatenating
                            # client would double-count)
                            replace[str(qi)] = full
                    if tail:
                        yield {"delta": tail}
                    if replace:
                        yield {"replace": replace}
                    latency = time.monotonic() - t0
                    final = {"done": True, "predictions": preds,
                             "info": {"worker_id":
                                      reply.get("worker_id"),
                                      "latency_s": latency,
                                      "failovers": attempts - 1,
                                      "trace_id": tid}}
                    self._c_queries.inc(len(queries))
                    self._c_requests.inc()
                    self._h_e2e.observe(latency)
                    self.traces.add_span(tid, "done",
                                         latency_s=round(latency, 4))
                    with self._lock:
                        self._latencies.append(latency)
                    break
                if final is None:
                    # this attempt's worker is gone: penalize it and
                    # re-submit with the delivered text as the prefix.
                    # Silence from a worker that never sent ANYTHING is
                    # ambiguous — a long prefill queued behind busy
                    # slots looks identical to death — so only a
                    # proven-then-silent worker feeds the breaker
                    # (saturation must not cascade into fast-fail 503s
                    # for unary traffic)
                    self._c_failover.inc()
                    if failover_reason not in (
                            "worker draining", "expired at worker") \
                            and saw_event:
                        # a drain rejection is voluntary and an
                        # expired rejection PROVES the worker alive
                        # and responsive — neither is breaker evidence
                        self.breakers.record_failure(wid)
                    tried.add(wid)
                    self.traces.add_span(tid, "worker_lost",
                                         worker=wid,
                                         reason=failover_reason)
        except ConnectionError as e:
            # the kvd went unreachable past the reconnect window
            # mid-stream: end with a RESUMABLE event carrying the
            # delivered text — the client SDK honors retry_after_s and
            # auto-resumes against the respawned (WAL-replayed) data
            # plane without re-paying delivered tokens
            verdict = self._data_plane_lost(tid, e)
            self._c_resumable.inc()
            final = {"done": True, "resumable": True,
                     "qid": qid, "trace_id": tid,
                     "partial": [acc.get(i)
                                 for i in range(len(queries))],
                     **verdict}
        except Exception as e:  # noqa: BLE001 — the SSE response is
            # already committed (200 + headers) when this generator
            # runs, so errors can't become an HTTP status: every
            # failure mode must surface as a terminal done event
            final = {"done": True, "error": f"{type(e).__name__}: {e}"}
        finally:
            if qid:
                try:
                    self.hub.discard_prediction_queue(qid)
                except Exception:  # rafiki: noqa[silent-except] —
                    pass           # cleanup is best-effort
        yield final

    def stats(self) -> Dict[str, Any]:
        """Counters + latency percentiles over the recent-request window
        (the BASELINE p50 metric; surfaced in ``GET /health``)."""
        self._refresh_membership()
        with self._lock:
            lat = sorted(self._latencies)
        n_req = int(self._c_requests.value)
        n_q = int(self._c_queries.value)
        lat_sum = self._h_e2e.sum

        def pct(p: float) -> float:
            return nearest_rank(lat, p)

        workers: Dict[str, Any] = {}
        for wid in list(self.worker_ids):  # snapshot: membership may
            # change under a concurrent scale event
            try:
                s = self.hub.get_worker_stats(wid)
            except Exception:  # rafiki: noqa[silent-except] —
                s = None       # health must not 500 on a hub hiccup
            if s is not None:
                workers[wid] = self._annotate_staleness(wid, s)
                self.router.observe(wid, s)  # /health readers keep the
                #                              load view fresh too
        return {"queries_served": n_q, "requests_served": n_req,
                "latency_sum_s": lat_sum, "latency_window_n": len(lat),
                "latency_p50_s": pct(0.50), "latency_p95_s": pct(0.95),
                "latency_p99_s": pct(0.99),
                # the same distribution from the FIXED-BUCKET histogram
                # (what /metrics exposes): coarser than the window
                # percentiles but covers the whole process lifetime —
                # the dashboard's e2e p50/p95 source
                "e2e_hist_p50_s": self._h_e2e.quantile(0.50),
                "e2e_hist_p95_s": self._h_e2e.quantile(0.95),
                "e2e_hist_count": self._h_e2e.count,
                # the latency/accuracy controller's live budget (equals
                # gather_timeout when adaptive gathering is off/warming)
                "gather_deadline_s": self._gather_deadline_s(),
                "adaptive_gather": self.adaptive_gather,
                # SLO / overload plane: class default, live backlog vs
                # the shed caps, shed decisions per class, and the
                # brownout ladder (docs/operations.md "Overload &
                # brownout")
                "slo": {"default": self.default_slo,
                        "shed_depths": dict(self.shed_depths),
                        "queue_depth": self.router.total_queue_depth(),
                        "requests_shed": int(self._c_shed.value),
                        **{k: int(v) for k, v in
                           self._shed_counts.snapshot().items()},
                        "brownout": self.brownout.snapshot()},
                # per-worker circuit-breaker state + fault counters
                # (trips/recoveries ride /metrics too)
                "breakers": self.breakers.snapshot(),
                # routing pool: membership, decision counters, affinity
                # hit rate, per-worker load view (docs/operations.md
                # "Scale-out & autoscaling")
                "router": self.router.snapshot(),
                "stream_failovers": int(self._c_failover.value),
                "requests_fast_failed": int(self._c_fast_fail.value),
                # data-plane survival: down flag + failure count (the
                # dashboard's data-plane banner reads this)
                "data_plane": self.data_plane_health(),
                # per-worker published counters (drop accounting, decode-
                # engine stats): a worker silently dropping expired
                # queries shows up HERE, not as mystery timeouts
                "workers": workers}

    def _annotate_staleness(self, wid: str, s: Dict[str, Any]
                            ) -> Dict[str, Any]:
        """Stamp ``stale`` onto a worker's published stats.

        Clock-step safe: the worker publishes a MONOTONIC ``uptime_s``
        and its own ``stale_after_s`` budget; this side tracks when the
        uptime last ADVANCED on its own monotonic clock. A worker whose
        uptime hasn't moved for longer than its budget is stale (dead,
        hung, or partitioned) — wall-clock ``published_at`` is kept in
        the payload for humans but no longer gates anything. Workers
        predating ``uptime_s`` fall back to the wall-clock test.

        The verdict also feeds the circuit-breaker board: a stale
        worker force-opens its breaker (the staleness signal is the
        liveness ground truth the gather-miss heuristic approximates),
        and the published ``draining`` flag sets/clears the board's
        drain exclusion — a respawned worker's fresh stats are what
        re-admit its id after a rolling restart."""
        s = dict(s)
        now = time.monotonic()
        up = s.get("uptime_s")
        budget = float(s.get("stale_after_s") or 60.0)
        if isinstance(up, (int, float)) and not isinstance(up, bool):
            with self._lock:
                last = self._worker_seen.get(wid)
                # any CHANGE refreshes the watermark: an advance is a
                # live publisher, and a DECREASE is a respawned worker
                # whose uptime restarted near 0 — without the `!=` a
                # healthy replacement would read stale until it outlived
                # its dead predecessor's uptime
                if last is None or up != last[0]:
                    self._worker_seen[wid] = (float(up), now)
                    s["stale"] = False
                else:
                    s["stale"] = (now - last[1]) > budget
        else:
            pub = s.get("published_at")
            s["stale"] = bool(
                isinstance(pub, (int, float))
                and time.time() - float(pub) > budget)  # rafiki: noqa[taint-wall-clock-flow] — fallback for workers predating the monotonic uptime_s pair
        if s["stale"]:
            self.breakers.record_stale(wid)
        if "draining" in s:
            self.breakers.set_draining(wid, bool(s["draining"]))
        return s


def _stack(queries: Sequence[Any]) -> Any:
    """Stack homogeneous array queries for compact transport; fall back to
    a list for ragged/object queries."""
    try:
        arrs = [np.asarray(q) for q in queries]
        # numeric/bool only: unicode/bytes/object arrays don't survive
        # the msgpack pytree codec (text queries ship as plain lists)
        if arrs and all(a.shape == arrs[0].shape and
                        a.dtype == arrs[0].dtype and
                        a.dtype.kind not in "USO" for a in arrs):
            return np.stack(arrs)
    except (TypeError, ValueError):
        pass
    return list(queries)


#: hard ceiling on client-supplied request timeouts: generous for any
#: legitimate generation (12x the default stream budget), small enough
#: that a stuck request eventually releases its handler thread + slot
MAX_REQUEST_TIMEOUT_S = 3600.0


class PredictorService:
    """HTTP front: POST /predict {queries} → {predictions}."""

    def __init__(self, predictor: Predictor, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.predictor = predictor
        self.http = JsonHttpService(host, port,
                                    registry=predictor.metrics)
        self.http.route("POST", "/predict", self._predict)
        self.http.route("POST", "/predict_stream", self._predict_stream)
        self.http.route("GET", "/health", self._health)
        # GET /metrics (Prometheus text) + GET /debug/requests?n=K
        mount_obs_routes(self.http, predictor.metrics, predictor.traces)

    @staticmethod
    def _trace_header(headers) -> Optional[str]:
        """The inbound ``X-Rafiki-Trace-Id``, case-insensitively (the
        stdlib handler hands headers through as sent)."""
        for k, v in (headers or {}).items():
            if k.lower() == "x-rafiki-trace-id":
                return v
        return None

    def start(self) -> Tuple[str, int]:
        return self.http.start()

    def stop(self) -> None:
        self.http.stop()

    @staticmethod
    def _parse_timeout(body) -> Tuple[bool, Any]:
        """(True, seconds-or-None) or (False, error). Absent/null means
        "server default"; an explicit non-numeric or non-positive value
        (e.g. 0) is a client error, not a silent fallback."""
        timeout = (body or {}).get("timeout")
        if timeout is None:
            return True, None
        if isinstance(timeout, bool):
            # bool subclasses int: {"timeout": true} would silently
            # become a 1-second deadline instead of a client error
            return False, "timeout must be a number"
        try:
            t = float(timeout)
        except (TypeError, ValueError):
            return False, "timeout must be a number"
        if not (t > 0.0) or not math.isfinite(t):
            # rejects 0, negatives, NaN, and Infinity — json.loads
            # accepts bare Infinity, and an inf deadline would pin a
            # handler thread (and a decode slot) forever
            return False, "timeout must be a finite number > 0"
        if t > MAX_REQUEST_TIMEOUT_S:
            # a huge FINITE deadline pins a handler thread (and a
            # decode slot) as effectively as inf would
            return False, (
                f"timeout must be <= {MAX_REQUEST_TIMEOUT_S:.0f}s")
        return True, t

    @staticmethod
    def _parse_slo(body) -> Tuple[bool, Any]:
        """(True, normalized-class-or-None) or (False, error). Absent/
        null means "job default"; an unknown class is a client error —
        silently serving a typo'd class as interactive would defeat
        the admission policy."""
        slo = (body or {}).get("slo")
        if slo is None:
            return True, None
        try:
            return True, normalize_slo(slo)
        except ValueError as e:
            return False, str(e)

    def _predict(self, _m, body, headers) -> Tuple[int, Any]:
        queries = (body or {}).get("queries")
        if not isinstance(queries, list) or not queries:
            return 400, {"error": "body must be {queries: [...]}"}
        ok, timeout = self._parse_timeout(body)
        if not ok:
            return 400, {"error": timeout}
        ok, slo = self._parse_slo(body)
        if not ok:
            return 400, {"error": slo}
        sampling = (body or {}).get("sampling")
        preds, info = self.predictor.predict(
            queries, timeout=timeout,
            sampling=sampling if isinstance(sampling, dict) else None,
            trace_id=self._trace_header(headers), slo=slo)
        if info["workers_answered"] == 0:
            if info.get("shed"):
                # structured SHED 503: overload backpressure on a
                # best-effort class — distinct from the breaker
                # fast-fail below (`shed: true` + brownout stage), so
                # clients can tell "come back later" from "fleet down"
                return 503, {"error": info["errors"][0]
                             if info.get("errors") else "shed",
                             "shed": True, "slo": info.get("slo"),
                             "brownout_stage":
                                 info.get("brownout_stage", 0),
                             "retry_after_s": info.get("retry_after_s",
                                                       1.0),
                             "info": info}
            if info.get("fast_fail"):
                # structured 503: every breaker open (or the whole
                # fleet draining, or the DATA PLANE down — flagged
                # top-level so HttpStatusError.data_plane_down types
                # it) — the client is told when retrying can possibly
                # help instead of burning its own timeout
                out = {"error": info["errors"][0]
                       if info.get("errors")
                       else "no worker available",
                       "retry_after_s": info.get("retry_after_s",
                                                 1.0),
                       "info": info}
                if info.get("data_plane_down"):
                    out["data_plane_down"] = True
                return 503, out
            return 504, {"error": "no worker answered in time",
                         "info": info}
        return 200, {"predictions": preds, "info": info}

    def _predict_stream(self, _m, body, headers) -> Tuple[int, Any]:
        """SSE: one ``data: <json>\\n\\n`` event per generator yield
        (token deltas, then the final done/error event)."""
        queries = (body or {}).get("queries")
        if not isinstance(queries, list) or not queries:
            return 400, {"error": "body must be {queries: [...]}"}
        ok, timeout = self._parse_timeout(body)
        if not ok:
            return 400, {"error": timeout}
        ok, slo = self._parse_slo(body)
        if not ok:
            return 400, {"error": slo}
        sampling = (body or {}).get("sampling")
        resume = (body or {}).get("resume")
        if resume is not None and not isinstance(resume, list):
            return 400, {"error": "resume must be a list of partial "
                                  "texts (one per query, null for "
                                  "none)"}
        gate = self.predictor._data_plane_gate("")
        if gate is not None:
            # pre-flight the down-gate into a REAL 503 (same reasoning
            # as the shed pre-flight below), typed for the SDK's
            # stream-open retry via data_plane_down
            return 503, {**gate, "info": {"data_plane_down": True}}
        shed = self.predictor.shed_verdict(slo)
        if shed is not None:
            # pre-flight the shed verdict into a REAL 503 — once the
            # SSE response commits (200 + headers) a shed could only
            # be a terminal event, invisible to plain HTTP clients
            return 503, {**shed, "info": {"shed": True}}
        events = self.predictor.predict_stream(
            queries, timeout=timeout,
            sampling=sampling if isinstance(sampling, dict) else None,
            trace_id=self._trace_header(headers),
            resume_partial=resume, slo=slo)

        def sse():
            import json as _json
            for ev in events:
                yield b"data: " + _json.dumps(ev).encode("utf-8") + b"\n\n"

        return 200, StreamResponse(sse())

    def _health(self, _m, _b, _h) -> Tuple[int, Any]:
        return 200, {"ok": True, **self.predictor.stats()}


def main(argv: Optional[list] = None) -> int:
    """Service entrypoint: ``python -m rafiki_tpu.serving.predictor``."""
    import argparse
    import json

    from ..utils.platform import apply_platform_env

    apply_platform_env()  # ensemble math is numpy; never claim the chips

    from .queues import KVQueueHub

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True,
                        help="JSON: {worker_ids, kv_host, kv_port, host, "
                             "port, port_file, gather_timeout}")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    # shorter reconnect window than the worker default: the predictor
    # is the latency surface, and anything past a couple of seconds of
    # stalling belongs to the down-gate's instant 503, not a hang
    hub = KVQueueHub(cfg["kv_host"], int(cfg["kv_port"]),
                     retry_window_s=float(
                         cfg.get("hub_retry_window_s", 2.0)))
    predictor = Predictor(hub, cfg["worker_ids"],
                          gather_timeout=float(cfg.get("gather_timeout",
                                                       30.0)),
                          adaptive_gather=bool(
                              cfg.get("adaptive_gather")),
                          breaker_fail_threshold=int(
                              cfg.get("breaker_fail_threshold", 3)),
                          breaker_cooldown_s=float(
                              cfg.get("breaker_cooldown_s", 2.0)),
                          stream_silence_timeout_s=float(
                              cfg.get("stream_silence_timeout_s",
                                      30.0)),
                          max_stream_failovers=int(
                              cfg.get("max_stream_failovers", 2)),
                          # live pool membership key (the inference job
                          # id): the router follows autoscale events
                          # published by the control plane
                          pool_id=str(cfg.get("pool_id", "")),
                          affinity_prefix_chars=int(
                              cfg.get("affinity_prefix_chars",
                                      Router.DEFAULT_PREFIX_CHARS)),
                          # SLO / overload controls (admin budget keys
                          # SLO_DEFAULT / SLO_SHED_*_DEPTH /
                          # SLO_P95_TARGET_S / SLO_BACKGROUND_MAX_NEW)
                          default_slo=str(cfg.get("default_slo", "")),
                          slo_shed_depths=cfg.get("slo_shed_depths"),
                          brownout_target_p95_s=float(
                              cfg.get("brownout_target_p95_s", 0.0)),
                          brownout_clamp_max_new=int(
                              cfg.get("brownout_clamp_max_new", 16)))
    svc = PredictorService(predictor, cfg.get("host", "127.0.0.1"),
                           int(cfg.get("port", 0)))
    host, port = svc.start()
    if cfg.get("port_file"):
        with open(cfg["port_file"], "w") as f:
            f.write(str(port))
    print(f"predictor on {host}:{port}", flush=True)
    svc.http.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
