"""KV-page shipment blobs for disaggregated prefill/decode serving.

The paged KV cache made pages the repo's transfer unit (PR 5); this
module makes them a WIRE unit. A prefill-role worker chews a prompt
through chunked prefill, extracts the slot's finished KV rows — every
cache leaf uniformly, so int8 KV pools and their scale rows ship
together — and forwards them over the hub to a decode-role worker,
which installs them into its own pool pages and starts the tight
single-token loop at the same position local prefill would have
reached. Token-exact by construction: the installed KV bytes are the
bytes local prefill would have produced (same module, same params,
same tokenizer → same rows).

Blobs are plain msgpack-able dicts (numpy leaves ride the ParamStore
codec the hub already uses), deliberately self-describing so the
decode side can VALIDATE before touching its cache: a mismatched
layout, page size, leaf signature, or adapter is a structured
``ValueError`` the worker degrades to a local re-prefill — never a
silently-wrong cache install (which would be a correct-looking wrong
answer) and never a shape error escaping mid-step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

KV_BLOB_VERSION = 1

#: blob["layout"] values: ``paged`` leaves are whole pool pages
#: ``(n_pages, page_size, …)``; ``rows`` leaves are exact logical rows
#: ``(covered, …)`` sliced from a contiguous cache
LAYOUT_PAGED = "paged"
LAYOUT_ROWS = "rows"

#: worker role knob values (the disaggregation switch). ``unified``
#: (the default) is the single-engine behavior every existing deploy
#: keeps: one worker prefills AND decodes.
ROLES = ("unified", "prefill", "decode")


def normalize_role(value: Any) -> str:
    """The one worker-role validator (worker config, admin budget
    path, tests). ``None``/empty → ``unified``; anything else must
    name a member of :data:`ROLES` — a typo'd role silently serving
    unified would defeat the placement policy."""
    if value is None:
        return "unified"
    s = str(value).strip().lower()
    if not s:
        return "unified"
    if s not in ROLES:
        raise ValueError(f"unknown worker role {value!r} "
                         f"(one of: {', '.join(ROLES)})")
    return s


def leaf_signature(leaves: Sequence[np.ndarray]) -> List[List[Any]]:
    """Per-leaf ``[trailing-shape, dtype]`` signature. The leading axis
    (pages shipped / rows covered) varies per request; everything after
    it is model geometry and must match the receiving engine exactly."""
    return [[list(a.shape[1:]), str(a.dtype)] for a in leaves]


def make_kv_blob(covered: int, layout: str, page_size: int,
                 leaves: Sequence[np.ndarray],
                 adapter_id: int = 0) -> Dict[str, Any]:
    """Package extracted KV rows for the hub. ``covered`` is the count
    of prefilled logical positions (``0..covered-1``); ``leaves`` are
    the cache's flattened leaves in ``jax.tree_util`` order (empty for
    single-token prompts, which have nothing prefilled)."""
    if layout not in (LAYOUT_PAGED, LAYOUT_ROWS):
        raise ValueError(f"unknown KV blob layout {layout!r}")
    arrs = [np.asarray(a) for a in leaves]
    return {"v": KV_BLOB_VERSION, "covered": int(covered),
            "layout": layout, "page_size": int(page_size),
            "adapter_id": int(adapter_id),
            "sig": leaf_signature(arrs), "leaves": arrs,
            "nbytes": int(sum(a.nbytes for a in arrs))}


def check_kv_blob(blob: Any, *, layout: str, page_size: int,
                  expect_sig: Sequence[Sequence[Any]],
                  prompt_len: int, adapter_id: int = 0,
                  expect_leading: Optional[int] = None
                  ) -> Dict[str, Any]:
    """Validate a shipped blob against the receiving engine BEFORE any
    cache write. Raises ``ValueError`` with an operator-readable reason
    on any mismatch; returns the blob. The decode worker catches the
    raise and falls back to a local re-prefill (token-exact, just
    slower) — degradation, not a hung stream or a wrong answer."""
    if not isinstance(blob, dict):
        raise ValueError("KV blob is not a mapping")
    if int(blob.get("v", -1)) != KV_BLOB_VERSION:
        raise ValueError(f"KV blob version {blob.get('v')!r} != "
                         f"{KV_BLOB_VERSION}")
    if blob.get("layout") != layout:
        raise ValueError(f"KV blob layout {blob.get('layout')!r} does "
                         f"not match this engine's ({layout!r})")
    if layout == LAYOUT_PAGED and int(blob.get("page_size", 0)) \
            != int(page_size):
        raise ValueError(
            f"KV blob page_size {blob.get('page_size')!r} != engine "
            f"page_size {page_size}")
    if int(blob.get("adapter_id", 0)) != int(adapter_id):
        # the KV is a function of the adapter that computed it:
        # installing another tenant's rows would be the wrong-tenant
        # answer the multi-adapter validation exists to prevent
        raise ValueError(
            f"KV blob adapter {blob.get('adapter_id')!r} != request "
            f"adapter {adapter_id}")
    covered = int(blob.get("covered", -1))
    if covered < 0 or covered > max(0, int(prompt_len) - 1):
        raise ValueError(
            f"KV blob covers {covered} positions but the prompt has "
            f"{prompt_len} tokens (at most prompt_len - 1 can be "
            "prefilled)")
    leaves = blob.get("leaves")
    if not isinstance(leaves, (list, tuple)):
        raise ValueError("KV blob has no leaves list")
    if covered > 0:
        sig = [[list(s), str(d)] for s, d in
               ((tuple(e[0]), e[1]) for e in blob.get("sig") or [])]
        want = [[list(s), str(d)] for s, d in
                ((tuple(e[0]), e[1]) for e in expect_sig)]
        if sig != want:
            raise ValueError(
                "KV blob leaf signature does not match this engine's "
                "cache (different model geometry / dtype / int8 "
                "setting)")
        if len(leaves) != len(want):
            # count BEFORE the per-leaf zip below (zip truncates): a
            # torn shipment with fewer leaves than its signature must
            # fail HERE, not as a tree_unflatten error inside step()
            raise ValueError(
                f"KV blob ships {len(leaves)} leaves but its "
                f"signature names {len(want)} (truncated shipment)")
        for a, (shape, dtype) in zip(leaves, blob["sig"]):
            # shape/dtype via attributes, NOT np.asarray: a device-
            # staged leaf (stage_kv_blob) must not pay a blocking d2h
            # sync just to be looked at
            if getattr(a, "shape", None) is None:
                a = np.asarray(a)
            arr = a
            if list(arr.shape[1:]) != list(shape) or \
                    str(arr.dtype) != str(dtype):
                raise ValueError("KV blob leaf does not match its own "
                                 "signature (corrupt shipment)")
            if expect_leading is not None and \
                    arr.shape[0] != int(expect_leading):
                raise ValueError(
                    f"KV blob leaf ships {arr.shape[0]} "
                    f"pages/rows, engine expects {expect_leading} "
                    f"for {covered} covered positions")
    return blob
