"""Affinity-aware, load-balanced request routing over a worker pool.

The predictor's streaming path places each request on ONE worker. A
round-robin cursor (the previous implementation) spreads load evenly but
ignores the two signals that actually dominate serving behavior at
scale:

- **Prefix-cache affinity.** Decode engines keep a prefix-snapshot
  store: a prompt whose prefix was prefilled on a worker before skips
  that prefill entirely (see ``DecodeEngine.register_prefix``). Under
  shared-prefix traffic — the common production shape: one system
  prompt, millions of user turns — TTFT is dominated by whether the
  request lands on the worker that already holds its prefix KV, not by
  FLOPs (the Gemma-on-TPU serving analysis, PAPERS.md). The router
  hashes each request's *affinity key* (its leading
  ``prefix_chars`` characters — the shared-system-prefix granularity)
  with **rendezvous (HRW) hashing** over the pool: identical prefixes
  always land on the same worker, and a membership change (scale-up,
  scale-down, crash) remaps only the keys owned by the
  departed/arriving worker — every other key keeps its warm cache.

- **Live load.** Workers already publish ``kv_pages_used`` /
  ``kv_pages_total``, ``admission_stalls``, and TTFT/queue p95s (PR 5/6
  gauges). When the affinity target is open, draining, or *saturated*
  (page pool nearly full, or stalling admissions), sending the request
  there anyway trades a prefill for a queue — strictly worse. The
  router then falls back to the least-loaded healthy worker, ranked on
  (stalling?, queue depth, page-pool ratio, queue-wait p95).

Health gating rides the :class:`~rafiki_tpu.serving.breaker
.BreakerBoard` the predictor already owns: only CLOSED, non-draining
workers are normal candidates; with none, at most ONE due open breaker
is probed (the selected request IS the half-open probe — flipping every
due breaker would record probes nobody sends traffic to). This subsumes
the open/draining-skip logic the old ``_pick_stream_worker`` carried.

Membership is dynamic: :meth:`add_worker` / :meth:`remove_worker` keep
the table consistent while the control-plane autoscaler grows and
shrinks the pool (the predictor applies hub-published membership
diffs). Decision counters + the affinity hit-rate ride the predictor's
``/metrics``.

Thread-safety: one lock guards members + load snapshots; the board has
its own. Selection is a few dict/hash operations — far cheaper than the
stream it places.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import (Any, Callable, Dict, List, Mapping, Optional,
                    Sequence, Tuple)

from ..obs.metrics import StatsMap
from .breaker import CLOSED, BreakerBoard


def _signal(stats: Mapping[str, Any], name: str) -> Optional[float]:
    """A numeric load signal from a published stats dict, accepting
    both the hub-publish spelling (``engine_kv_pages_used``) and the
    bare engine spelling (``kv_pages_used``)."""
    for key in (f"engine_{name}", name):
        v = stats.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None


class _Load:
    """Latest observed load signals for one worker."""

    __slots__ = ("pages_ratio", "stalls_total", "stalled_until",
                 "queue_depth", "wait_p95_s", "class_backlog", "role",
                 "at")

    def __init__(self) -> None:
        #: disaggregated serving role the worker publishes
        #: ("unified" / "prefill" / "decode"); unknown until the first
        #: stats sample — treated as unified (serves everything)
        self.role = "unified"
        self.pages_ratio = 0.0    # kv_pages_used / kv_pages_total
        #: cumulative admission_stalls counter; None until the first
        #: sample — the first sight is a BASELINE, not growth (a fresh
        #: predictor must not read a long-lived worker's historical
        #: stall total as "stalling right now")
        self.stalls_total: Optional[float] = None
        self.stalled_until = 0.0  # recent stall growth holds 'saturated'
        self.queue_depth = 0      # unpopped messages on the query queue
        self.wait_p95_s = 0.0     # queue-wait p95 (fallback: TTFT p95)
        #: engine-side per-class admission backlog (the `queued_*`
        #: gauges): workers pop the hub eagerly, so the REAL backlog
        #: under overload sits in the engine's class queue, not the
        #: hub — the SLO shed gate reads it from here
        self.class_backlog: Dict[str, int] = {}
        self.at = 0.0


class Router:
    """Single-worker placement: HRW prefix affinity, load-aware
    fallback, breaker-gated health."""

    #: affinity target with its page pool this full is *saturated*:
    #: placing there trades a prefill for an admission stall
    SATURATION_PAGES_RATIO = 0.95
    #: a stall-counter increase marks the worker saturated this long
    #: (stalls are cumulative; the hold turns deltas into a level)
    STALL_HOLD_S = 5.0
    #: affinity-key granularity: requests sharing this many leading
    #: characters colocate (the shared-system-prefix scale; snapshot
    #: prefixes shorter than this still hit — their requests agree on
    #: far more than the key)
    DEFAULT_PREFIX_CHARS = 64

    def __init__(self, worker_ids: Sequence[str], board: BreakerBoard,
                 prefix_chars: int = DEFAULT_PREFIX_CHARS,
                 now: Callable[[], float] = time.monotonic) -> None:
        self._board = board
        self._now = now
        self.prefix_chars = max(1, int(prefix_chars))
        self._lock = threading.Lock()
        self._members: List[str] = list(dict.fromkeys(worker_ids))
        self._load: Dict[str, _Load] = {}
        #: routing decisions, registry-ready (the predictor merges
        #: these onto its /metrics)
        self.counters = StatsMap({
            "router_affinity_hits": 0,       # key's HRW owner chosen
            "router_affinity_redirects": 0,  # owner unusable → fallback
            "router_least_loaded_picks": 0,  # load-ranked fallback used
            "router_probe_picks": 0,         # no closed worker: this
            #                                  request is the half-open
            #                                  probe
            "router_no_candidate": 0,        # nothing selectable
            "router_prefill_picks": 0})      # prefill legs placed on a
        #                                      prefill-role worker

    # ---- membership ----
    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def __contains__(self, wid: str) -> bool:
        with self._lock:
            return wid in self._members

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def add_worker(self, wid: str) -> None:
        with self._lock:
            if wid not in self._members:
                self._members.append(wid)

    def remove_worker(self, wid: str) -> None:
        with self._lock:
            if wid in self._members:
                self._members.remove(wid)
            self._load.pop(wid, None)

    # ---- affinity ----
    def affinity_key(self, queries: Optional[Sequence[Any]]
                     ) -> Optional[str]:
        """The request's affinity key: the leading ``prefix_chars``
        characters of its first text query. Non-text queries
        (classification vectors) have no prefix cache to hit — None,
        and the request is placed purely by load."""
        if not queries:
            return None
        q = queries[0]
        if not isinstance(q, str) or not q:
            return None
        return q[:self.prefix_chars]

    @staticmethod
    def _score(key: str, wid: str) -> int:
        """HRW weight of (key, worker): highest score owns the key.
        A worker leaving only remaps the keys *it* owned (everyone
        else's top pick is unchanged); a worker joining only claims
        the keys it now scores highest on."""
        h = hashlib.blake2b(f"{key}\x00{wid}".encode("utf-8", "replace"),
                            digest_size=8)
        return int.from_bytes(h.digest(), "big")

    def owner(self, key: str, exclude: Sequence[str] = ()) -> Optional[str]:
        """The key's HRW owner among current members minus ``exclude``
        (for a failover retry the natural successor owner — still the
        minimal remap)."""
        with self._lock:
            cands = [w for w in self._members if w not in exclude]
        if not cands:
            return None
        return max(cands, key=lambda w: self._score(key, w))

    # ---- load signals ----
    def observe(self, wid: str, stats: Mapping[str, Any]) -> None:
        """Fold a worker's published stats into its load snapshot (the
        predictor feeds these on its rate-limited refresh)."""
        now = self._now()
        used = _signal(stats, "kv_pages_used")
        total = _signal(stats, "kv_pages_total")
        stalls = _signal(stats, "admission_stalls")
        p95 = stats.get("queue_p95_s")
        if not isinstance(p95, (int, float)) or isinstance(p95, bool):
            p95 = stats.get("ttft_p95_s")
        with self._lock:
            ld = self._load.get(wid)
            if ld is None:
                ld = self._load[wid] = _Load()
            if used is not None and total:
                ld.pages_ratio = max(0.0, used / total)
            if stalls is not None:
                if ld.stalls_total is not None and \
                        stalls > ld.stalls_total:
                    # the counter moved since last look: admissions are
                    # stalling NOW — hold the saturation verdict
                    ld.stalled_until = now + self.STALL_HOLD_S
                ld.stalls_total = stalls
            if isinstance(p95, (int, float)) and not isinstance(p95, bool):
                ld.wait_p95_s = float(p95)
            role = stats.get("role")
            if isinstance(role, str) and role in ("unified", "prefill",
                                                  "decode"):
                ld.role = role
            for cls in ("interactive", "batch", "background"):
                q = _signal(stats, f"queued_{cls}")
                if q is not None:
                    ld.class_backlog[cls] = int(q)
            ld.at = now

    def observe_queue_depth(self, wid: str, depth: int) -> None:
        with self._lock:
            ld = self._load.get(wid)
            if ld is None:
                ld = self._load[wid] = _Load()
            ld.queue_depth = max(0, int(depth))

    def _backlog_members(self) -> List[str]:
        """Members whose backlog gauges are TRUSTWORTHY serving
        backlog: breaker CLOSED only. A dead/stale worker's breaker
        force-opens, and its last-published ``queued_*`` gauges
        describe a corpse — summing them would pin the shed gate shut
        on an idle fleet (the same corpse-pins-the-controller hazard
        as the brownout p95 feed). Prefill-role workers are excluded
        too: a disaggregated request already counts once on its decode
        worker, and summing the prefill leg's queues would double-
        count every shipment (shedding below the operator's depth cap
        while decode capacity sits idle)."""
        snap = self._board.snapshot()
        with self._lock:
            return [w for w in self._members
                    if (snap.get(w) or {}).get("state") == CLOSED
                    and (w not in self._load
                         or self._load[w].role != "prefill")]

    def total_queue_depth(self) -> int:
        """Unpopped query-queue messages summed over live (breaker-
        CLOSED) members — the predictor's SLO shed gate compares this
        against the per-class depth caps (a fleet-level backlog
        level, refreshed on the same rate-limited tick as the load
        view)."""
        members = self._backlog_members()
        with self._lock:
            return sum(self._load[w].queue_depth for w in members
                       if w in self._load)

    def class_backlog(self, slo: str) -> int:
        """Fleet-wide ENGINE admission backlog for one SLO class (the
        live members' published ``queued_<class>`` gauges summed).
        Workers pop the hub eagerly, so under overload the backlog
        lives in the engines' class queues — hub depth alone
        under-measures it."""
        members = self._backlog_members()
        with self._lock:
            return sum(self._load[w].class_backlog.get(slo, 0)
                       for w in members if w in self._load)

    def saturated(self, wid: str) -> bool:
        """True when placing a request on ``wid`` would likely stall at
        admission: page pool ~full, or its stall counter grew within
        the last ``STALL_HOLD_S``. Workers with no signals yet (fresh
        scale-up) read as unsaturated — new capacity should attract
        traffic."""
        now = self._now()
        with self._lock:
            ld = self._load.get(wid)
            if ld is None:
                return False
            return (ld.pages_ratio >= self.SATURATION_PAGES_RATIO
                    or now < ld.stalled_until)

    def _rank(self, wid: str, idx: int) -> Tuple:
        """Least-loaded ordering: stalling last, then queue depth,
        page-pool pressure, queue-wait p95; member index keeps ties
        deterministic."""
        now = self._now()
        with self._lock:
            ld = self._load.get(wid)
            if ld is None:
                return (0, 0, 0.0, 0.0, idx)
            return (1 if now < ld.stalled_until else 0, ld.queue_depth,
                    ld.pages_ratio, ld.wait_p95_s, idx)

    def role_of(self, wid: str) -> str:
        """The worker's published disaggregation role (``unified``
        until its first stats sample says otherwise)."""
        with self._lock:
            ld = self._load.get(wid)
            return ld.role if ld is not None else "unified"

    # ---- selection ----
    def select(self, key: Optional[str] = None,
               exclude: Sequence[str] = ()) -> Optional[str]:
        """Pick ONE worker for a request's DECODE leg.

        Order: the key's HRW owner when healthy and unsaturated
        (affinity hit) → least-loaded healthy worker (redirect /
        keyless placement) → at most one due half-open probe → None
        (no candidate; the caller's resumable-error path).

        ``prefill``-role workers are excluded: they exist to chew
        prompts and ship KV pages (:meth:`select_prefill`), and a
        stream placed there would decode on the wrong side of the
        split. The HRW hash ALSO skips them, so a worker flipping
        role only remaps its own keys — the affinity minimal-remap
        property survives disaggregation. When the pool is prefill-
        only (a misconfiguration), they serve anyway: degraded beats
        unservable."""
        with self._lock:
            members = list(self._members)
            serving = [w for w in members
                       if w not in exclude
                       and (w not in self._load
                            or self._load[w].role != "prefill")]
        cands = serving or [w for w in members if w not in exclude]
        if not cands:
            self.counters.inc("router_no_candidate")
            return None
        snap = self._board.snapshot()

        def _healthy(w: str) -> bool:
            st = snap.get(w)
            return (st is not None and st.get("state") == CLOSED
                    and not st.get("draining"))

        healthy = [w for w in cands if _healthy(w)]
        if healthy:
            if key is not None:
                target = max(cands, key=lambda w: self._score(key, w))
                if target in healthy and not self.saturated(target):
                    self.counters.inc("router_affinity_hits")
                    return target
                self.counters.inc("router_affinity_redirects")
            open_pool = [w for w in healthy if not self.saturated(w)]
            pool = open_pool or healthy  # all saturated: overload is
            #                              everywhere, pick the least bad
            pick = min(pool,
                       key=lambda w: self._rank(w, members.index(w)))
            self.counters.inc("router_least_loaded_picks")
            return pick
        for w in cands:
            if self._board.allow(w):
                # this request IS the half-open probe (allow() flips
                # exactly one due breaker per call)
                self.counters.inc("router_probe_picks")
                return w
        self.counters.inc("router_no_candidate")
        return None

    def select_prefill(self, exclude: Sequence[str] = ()
                       ) -> Optional[str]:
        """Pick the worker for a request's PREFILL leg: the
        least-loaded healthy ``prefill``-role member, or None when the
        pool has none (the caller serves unified — prefill runs on the
        decode worker exactly as before disaggregation). No probe
        fallback here: the prefill leg is an optimization, and probing
        a sick worker with it would spend the half-open budget on
        traffic whose failure is invisible (fire-and-forget)."""
        with self._lock:
            members = list(self._members)
            cands = [w for w in members
                     if w not in exclude and w in self._load
                     and self._load[w].role == "prefill"]
        if not cands:
            return None
        snap = self._board.snapshot()
        healthy = [w for w in cands
                   if (st := snap.get(w)) is not None
                   and st.get("state") == CLOSED
                   and not st.get("draining")]
        if not healthy:
            return None
        pick = min(healthy,
                   key=lambda w: self._rank(w, members.index(w)))
        self.counters.inc("router_prefill_picks")
        return pick

    # ---- read-out ----
    def affinity_hit_rate(self) -> float:
        """Fraction of keyed selections that landed on their HRW owner
        (the prefix-cache hit proxy the /metrics gauge exposes). 0.0
        before any keyed traffic."""
        hits = float(self.counters["router_affinity_hits"])
        misses = float(self.counters["router_affinity_redirects"])
        total = hits + misses
        return hits / total if total else 0.0

    def snapshot(self) -> Dict[str, Any]:
        """Router state for /health: membership + decision counters +
        hit rate + per-worker load view."""
        now = self._now()
        with self._lock:
            load = {wid: {"pages_ratio": round(ld.pages_ratio, 4),
                          "queue_depth": ld.queue_depth,
                          "wait_p95_s": round(ld.wait_p95_s, 4),
                          "stalled": now < ld.stalled_until,
                          "role": ld.role}
                    for wid, ld in self._load.items()}
            members = list(self._members)
        return {"members": members,
                "affinity_hit_rate": round(self.affinity_hit_rate(), 4),
                **{k: int(v) for k, v in self.counters.snapshot().items()},
                "load": load}
