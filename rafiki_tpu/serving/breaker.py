"""Per-worker circuit breakers for the serving request path.

The predictor's gather treats a dead worker exactly like a slow one: it
burns the whole gather budget waiting for a reply that can never come,
on EVERY request, until an operator notices. The reference paper's
predictor model (SURVEY.md §3.3) assumes replicas either answer or miss
a deadline — production workers also *die mid-request*. This module is
the request-path failure detector the respawn machinery
(``ServicesManager``) is to the control plane:

- one closed/open/half-open state machine per ``worker_id``, fed by
  gather answer/miss outcomes and by the monotonic ``uptime_s``
  staleness signal the workers already publish;
- **open** workers are skipped at scatter time (the gather quorum
  shrinks accordingly — less ensemble accuracy, none of the dead
  replica's latency, the paper's latency/accuracy axis applied to
  liveness);
- after a cooldown one request is let through as a **half-open probe**;
  its outcome closes the breaker or re-opens it with an exponentially
  backed-off cooldown;
- when every worker is open the predictor fast-fails with a structured
  503 + ``retry_after_s`` instead of burning the timeout — the board
  knows when the next probe is due, so the client is told exactly when
  retrying can possibly help.

Draining workers (graceful drain / rolling restart) ride the same
board: a ``draining`` flag excludes a worker from scatter without
counting as a failure — drain is voluntary and self-clearing, not an
outage.

Thread-safety: one lock for the whole board. Every operation is a few
dict/float touches — far cheaper than the scatter it guards.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..obs.metrics import StatsMap

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class _Breaker:
    """State for one worker. Touched only under the board's lock."""

    __slots__ = ("state", "fails", "opened_at", "cooldown_s",
                 "probe_at", "draining")

    def __init__(self) -> None:
        self.state = CLOSED
        self.fails = 0          # consecutive misses while closed
        self.opened_at = 0.0    # board-clock time of the last trip
        self.cooldown_s = 0.0   # current open→probe wait
        self.probe_at = 0.0     # board-clock time the probe was issued
        self.draining = False


class BreakerBoard:
    """Circuit breakers for a (dynamic) fleet of worker ids.

    Membership follows the pool: :meth:`add_worker` admits a scale-up
    replica (CLOSED), :meth:`remove_worker` drops a departed one —
    outcome feeds for non-members are no-ops so an in-flight gather
    finishing after a scale-down cannot resurrect the id.

    ``fail_threshold`` consecutive misses trip a breaker open;
    ``cooldown_s`` later one probe is admitted (half-open), and each
    failed probe doubles the cooldown up to ``max_cooldown_s`` — a
    worker that stays dead costs one probe per cooldown, not a timeout
    per request. ``now`` is injectable for deterministic tests.
    """

    def __init__(self, worker_ids: Sequence[str],
                 fail_threshold: int = 3, cooldown_s: float = 2.0,
                 max_cooldown_s: float = 60.0,
                 now: Callable[[], float] = time.monotonic) -> None:
        self.fail_threshold = max(1, int(fail_threshold))
        self.cooldown_s = max(0.05, float(cooldown_s))
        self.max_cooldown_s = max(self.cooldown_s, float(max_cooldown_s))
        self._now = now
        self._lock = threading.Lock()
        self._b: Dict[str, _Breaker] = {w: _Breaker()
                                        for w in worker_ids}
        #: trip/recovery accounting, registry-ready (the predictor
        #: merges these onto its /metrics)
        self.counters = StatsMap({"breaker_trips": 0,
                                  "breaker_recoveries": 0,
                                  "breaker_probes": 0,
                                  "breaker_stale_trips": 0})

    def _get(self, wid: str) -> Optional[_Breaker]:
        """The worker's breaker, or None for a non-member. Unknown ids
        are NOT lazily created: after :meth:`remove_worker` a straggling
        outcome feed (an in-flight gather finishing) must not resurrect
        state for a worker the pool no longer contains — ``targets()``
        iterates this dict, so a resurrected entry would be scattered
        to forever."""
        return self._b.get(wid)

    # ---- dynamic membership (pool scale-out) ----
    def add_worker(self, wid: str) -> None:
        """Admit a new pool member; it starts CLOSED."""
        with self._lock:
            if wid not in self._b:
                self._b[wid] = _Breaker()

    def remove_worker(self, wid: str) -> None:
        """Drop a departed member's breaker state entirely (scale-down,
        not an outage: no trip is recorded)."""
        with self._lock:
            self._b.pop(wid, None)

    # ---- scatter-time gating ----
    def _due(self, b: _Breaker, now: float) -> bool:
        """True when an OPEN breaker's cooldown has elapsed, or a
        HALF_OPEN probe went unanswered long enough to re-issue (the
        probe request's process may have died mid-gather)."""
        if b.state == OPEN:
            return now - b.opened_at >= b.cooldown_s
        if b.state == HALF_OPEN:
            return now - b.probe_at >= max(b.cooldown_s, self.cooldown_s)
        return False

    def targets(self) -> List[str]:
        """Worker ids a new request may scatter to right now: closed
        breakers plus open ones whose probe is due (issuing the probe —
        the caller's scatter IS the probe). Draining workers are
        excluded. Order follows construction order."""
        now = self._now()
        out: List[str] = []
        with self._lock:
            for wid, b in self._b.items():
                if b.draining:
                    continue
                if b.state == CLOSED:
                    out.append(wid)
                elif self._due(b, now):
                    b.state = HALF_OPEN
                    b.probe_at = now
                    self.counters.inc("breaker_probes")
                    out.append(wid)
        return out

    def allow(self, wid: str) -> bool:
        """Single-worker variant of :meth:`targets` (stream routing).
        Non-members are never admittable."""
        now = self._now()
        with self._lock:
            b = self._get(wid)
            if b is None or b.draining:
                return False
            if b.state == CLOSED:
                return True
            if self._due(b, now):
                b.state = HALF_OPEN
                b.probe_at = now
                self.counters.inc("breaker_probes")
                return True
            return False

    # ---- outcome feeds ----
    def record_success(self, wid: str) -> None:
        """An answer (or stream delta) arrived from ``wid``: close a
        half-open breaker (probe succeeded), clear the miss streak. A
        reply also proves the worker is past any drain it advertised
        earlier only when it is a real answer — callers clear draining
        explicitly via :meth:`set_draining`."""
        with self._lock:
            b = self._get(wid)
            if b is None:
                return  # removed mid-gather: nothing to close
            if b.state != CLOSED:
                self.counters.inc("breaker_recoveries")
            b.state = CLOSED
            b.fails = 0
            b.cooldown_s = 0.0

    def record_failure(self, wid: str) -> None:
        """A gather miss / stream silence from ``wid``: trips the
        breaker after ``fail_threshold`` consecutive misses; a failed
        half-open probe re-opens immediately with doubled cooldown."""
        now = self._now()
        with self._lock:
            b = self._get(wid)
            if b is None:
                return  # removed mid-gather: a miss on a non-member
            if b.state == HALF_OPEN:
                b.cooldown_s = min(self.max_cooldown_s,
                                   max(self.cooldown_s,
                                       b.cooldown_s * 2.0))
                b.state = OPEN
                b.opened_at = now
                self.counters.inc("breaker_trips")
                return
            b.fails += 1
            if b.state == CLOSED and b.fails >= self.fail_threshold:
                b.state = OPEN
                b.opened_at = now
                b.cooldown_s = self.cooldown_s
                self.counters.inc("breaker_trips")

    def record_stale(self, wid: str) -> None:
        """The worker's published ``uptime_s`` stopped advancing past
        its own staleness budget (PR 6's monotonic liveness signal):
        force the breaker open without waiting for miss accumulation —
        a stale publisher is dead/hung/partitioned, not slow."""
        now = self._now()
        with self._lock:
            b = self._get(wid)
            if b is not None and b.state == CLOSED:
                b.state = OPEN
                b.opened_at = now
                b.cooldown_s = b.cooldown_s or self.cooldown_s
                self.counters.inc("breaker_trips")
                self.counters.inc("breaker_stale_trips")

    def set_draining(self, wid: str, draining: bool) -> None:
        with self._lock:
            b = self._get(wid)
            if b is not None:
                b.draining = bool(draining)

    def any_draining(self) -> bool:
        """O(n) under the lock — the scatter path's cheap guard for
        'is a drain-exclusion refresh even worth considering'."""
        with self._lock:
            return any(b.draining for b in self._b.values())

    # ---- read-out ----
    def retry_after_s(self) -> float:
        """Seconds until the next half-open probe is due across the
        fleet — the ``retry_after_s`` a fast-failed 503 carries. 0 when
        some worker is already admittable (callers shouldn't have
        fast-failed); the base cooldown when every breaker is draining
        (drain ends on its own schedule, the cooldown is a sane poll
        interval)."""
        now = self._now()
        best: Optional[float] = None
        with self._lock:
            for b in self._b.values():
                if b.draining:
                    continue
                if b.state == CLOSED or self._due(b, now):
                    return 0.0
                if b.state == OPEN:
                    wait = b.cooldown_s - (now - b.opened_at)
                else:  # HALF_OPEN: probe outstanding, re-issue later
                    wait = max(b.cooldown_s, self.cooldown_s) \
                        - (now - b.probe_at)
                if best is None or wait < best:
                    best = wait
        return max(0.0, best if best is not None else self.cooldown_s)

    def state(self, wid: str) -> str:
        with self._lock:
            b = self._b.get(wid)
            return b.state if b is not None else CLOSED

    def n_open(self) -> int:
        """Workers currently not admittable (open/half-open/draining) —
        the live gauge on /metrics."""
        with self._lock:
            return sum(1 for b in self._b.values()
                       if b.draining or b.state != CLOSED)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Per-worker breaker state for /health."""
        with self._lock:
            return {wid: {"state": b.state, "fails": b.fails,
                          "draining": b.draining,
                          "cooldown_s": round(b.cooldown_s, 3)}
                    for wid, b in self._b.items()}
