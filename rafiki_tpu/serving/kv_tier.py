"""Host-RAM KV page tier: a second page pool behind the HBM allocator.

The paged KV cache (PR 5) made admission scale with LIVE tokens, but
every live page still had to sit in HBM — serviceable concurrency was
hard-capped by on-chip memory because each request RESERVES its
worst-case pages up front. This module adds the tier every production
serving stack converged on (the Gemma-on-TPU paper's
HBM-capacity-vs-throughput analysis, PAPERS.md): cold pages spill to a
pinned-host page pool and a prefetcher pulls them back ahead of the
compiled step that needs them, so the admission budget becomes
``HBM pages + host pages`` while the step program only ever touches
HBM-resident pages.

Division of labor:

- The :class:`~rafiki_tpu.serving.decode_engine.DecodeEngine` owns the
  POLICY: which slots park, which pages evict, when a parked slot
  resumes. It runs on the step thread and never blocks on a transfer —
  the lint rule ``blocking-transfer-in-decode-loop`` enforces exactly
  that.
- :class:`HostPageTier` owns the MECHANISM: a preallocated host pool
  (one buffer per cache leaf, page-major like the device pool), a free
  list, and a transfer thread that drains device→host copies
  (eviction) and stages host→device uploads (prefetch) off the hot
  loop. The step thread hands the tier already-gathered device arrays
  and picks up already-staged device arrays; the only blocking waits
  live on the TIER thread.

Safety: the transfer thread never touches the engine's cache (which is
donated to every compiled call). Evictions read from independent
gather results — JAX's buffer ordering guarantees the gather completes
before a later donated step reuses the source pages — and prefetch
stages fresh device arrays the step thread scatters in itself.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class _Ticket:
    """One queued transfer: completion event + enough context for the
    worker thread to run it."""

    __slots__ = ("kind", "key", "host_ids", "payload", "done", "at",
                 "failed")

    def __init__(self, kind: str, key: Any, host_ids: List[int],
                 payload: Any) -> None:
        self.kind = kind            # "evict" | "prefetch"
        self.key = key
        self.host_ids = host_ids
        self.payload = payload
        self.done = threading.Event()
        self.at = time.monotonic()
        #: the transfer raised; for evictions the retained ``payload``
        #: lets :meth:`HostPageTier.fetch` retry the copy — the host
        #: pool bytes for ``host_ids`` are NOT valid until it does
        self.failed = False


class HostPageTier:
    """Pinned-host page pool + async transfer worker.

    ``n_pages`` host pages, each the same ``(page_size, …)`` geometry
    as the device pool's pages (the pool buffers are allocated lazily
    on the first eviction, when the leaf shapes/dtypes are known).
    ``stats`` is the owning engine's StatsMap — the tier feeds the
    ``kv_host_pages_used/total``, ``kv_evictions_total``,
    ``kv_prefetch_hits/misses``, and ``kv_transfer_bytes_total``
    gauges the worker surfaces on ``/metrics``. ``observe_transfer``
    (wired by the worker) receives each completed transfer's wall
    seconds for the transfer-latency histogram.
    """

    def __init__(self, n_pages: int, stats: Any,
                 observe_transfer: Optional[Callable[[float], None]]
                 = None) -> None:
        if int(n_pages) < 1:
            raise ValueError("host tier needs >= 1 host page")
        self.n_pages = int(n_pages)
        self.stats = stats
        self.observe_transfer = observe_transfer
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self._pool: Optional[List[np.ndarray]] = None
        #: host page id -> the eviction ticket that is (or was) writing
        #: it; fetch/prefetch wait on these before reading the pool
        self._writers: Dict[int, _Ticket] = {}
        #: staged prefetches: key -> (host_ids, device leaves, ticket)
        self._staged: Dict[Any, Tuple[Tuple[int, ...], Any, _Ticket]] = {}
        #: park keys with a live prefetch interest. Park keys are
        #: monotonic and never reused, so a prefetch that completes
        #: after its key died (slot seated/preempted before the tier
        #: thread got there) must NOT store under it — nothing would
        #: ever take or drop that entry and the staged device arrays
        #: would stay pinned for the engine's lifetime.
        self._want: set = set()
        self._q: "collections.deque[_Ticket]" = collections.deque()
        self._cv = threading.Condition(self._lock)
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="kv-host-tier", daemon=True)
        self._thread.start()

    # ---- allocator (step thread) ----
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` host pages, or None when the tier is too full —
        the engine's combined-budget reservation makes None unreachable
        for within-reservation growth (see the allocator invariant in
        ``decode_engine.py``), but the tier still refuses rather than
        corrupting its free list."""
        with self._lock:
            if len(self._free) < n:
                return None
            ids = [self._free.pop() for _ in range(n)]
            self.stats.set("kv_host_pages_used",
                           self.n_pages - len(self._free))
        return ids

    def free(self, host_ids: Sequence[int]) -> None:
        with self._lock:
            for h in host_ids:
                self._writers.pop(int(h), None)
                self._free.append(int(h))
            self.stats.set("kv_host_pages_used",
                           self.n_pages - len(self._free))

    # ---- eviction (device -> host) ----
    def evict_submit(self, host_ids: List[int], device_leaves: Any
                     ) -> None:
        """Queue a device→host page copy. ``device_leaves`` are
        already-GATHERED per-leaf device arrays shaped
        ``(len(host_ids), page_size, …)`` — the step thread dispatched
        the gather and returns immediately; the d2h sync happens on the
        tier thread."""
        t = _Ticket("evict", None, [int(h) for h in host_ids],
                    device_leaves)
        with self._lock:
            for h in t.host_ids:
                self._writers[h] = t
            if self._stop:
                # close() raced a still-stepping engine: nothing will
                # ever pop this ticket, and a later fetch() would wait
                # its done event forever. Mark it failed-with-payload
                # so fetch's recovery path copies synchronously.
                t.failed = True
                t.done.set()
                return
            self._q.append(t)
            self._cv.notify()

    # ---- prefetch / fetch (host -> device) ----
    def prefetch_submit(self, key: Any, host_ids: Sequence[int]) -> None:
        """Ask the tier thread to stage ``key``'s host pages as device
        arrays ahead of the unpark that will need them. Idempotent per
        (key, ids); a stale staging for different ids is dropped."""
        ids = tuple(int(h) for h in host_ids)
        if not ids:
            return
        with self._lock:
            if self._stop:
                return
            self._want.add(key)
            cur = self._staged.get(key)
            if cur is not None and cur[0] == ids:
                return
            if cur is not None:
                self._staged.pop(key, None)
            if any(t.kind == "prefetch" and t.key == key
                   for t in self._q):
                return
            self._q.append(_Ticket("prefetch", key, list(ids), None))
            self._cv.notify()

    def take_staged(self, key: Any, host_ids: Sequence[int]
                    ) -> Optional[Any]:
        """The staged device leaves for ``key`` if the prefetcher got
        there first (and for the SAME pages) — a prefetch hit. None is
        a miss; the caller falls back to :meth:`fetch` + its own
        upload."""
        ids = tuple(int(h) for h in host_ids)
        with self._lock:
            cur = self._staged.pop(key, None)
            self._want.discard(key)
        if cur is None or cur[0] != ids or not cur[2].done.is_set():
            return None
        return cur[1]

    def drop_staged(self, key: Any) -> None:
        with self._lock:
            self._staged.pop(key, None)
            self._want.discard(key)

    def fetch(self, host_ids: Sequence[int]) -> List[np.ndarray]:
        """The host copies of the given pages, waiting out any pending
        eviction writes first. Runs on whatever thread asks — the
        engine only calls it on a prefetch MISS (the upload it then
        performs is host→device, which does not stall the device
        pipeline the way a d2h sync does)."""
        ids = [int(h) for h in host_ids]
        with self._lock:
            waits = [self._writers[h] for h in ids
                     if h in self._writers]
        for t in waits:
            t.done.wait()
        for t in {id(t): t for t in waits if t.failed}.values():
            self._recover_failed(t)
        with self._lock:
            # re-read AFTER the waits: the first-ever eviction creates
            # the pool on the tier thread, so a fetch racing it must
            # not capture the pre-creation None
            pool = self._pool
        if pool is None:
            raise RuntimeError("host tier fetch before any eviction")
        idx = np.asarray(ids, np.int64)
        return [leaf[idx] for leaf in pool]

    def _recover_failed(self, t: _Ticket) -> None:
        """Synchronously retry a failed eviction copy from the
        ticket's retained device payload (transient d2h errors clear;
        the gathered arrays were kept alive exactly for this). Raises
        if the content is unrecoverable: a lost page must be LOUD —
        the engine's step-level error recovery resets rather than
        resuming a stream from silently-zero KV. Held under the tier
        lock: two fetchers racing the same ticket must not double-run
        the copy or see a half-cleared payload."""
        with self._lock:
            if not t.failed:
                return  # another fetcher already recovered it
            leaves = t.payload
            if leaves is None:
                raise RuntimeError(
                    "kv host tier: evicted page content lost "
                    f"(pages {t.host_ids})")
            pool = self._ensure_pool(leaves)
            idx = np.asarray(t.host_ids, np.int64)
            moved = 0
            for buf, dev in zip(pool, leaves):
                arr = np.asarray(dev)
                buf[idx] = arr
                moved += arr.nbytes
            t.payload = None
            t.failed = False
            self.stats.inc("kv_evictions_total", len(t.host_ids))
            self.stats.inc("kv_transfer_bytes_total", moved)

    # ---- lifecycle ----
    def reset(self) -> None:
        with self._lock:
            self._q.clear()
            self._staged.clear()
            self._want.clear()
            self._writers.clear()
            self._free = list(range(self.n_pages - 1, -1, -1))
            self.stats.set("kv_host_pages_used", 0)

    def close(self) -> None:
        with self._lock:
            self._stop = True
            pending = list(self._q)
            self._q.clear()
            self._cv.notify()
        for t in pending:
            # never-executed work must not strand a fetch() waiting on
            # its done event: a failed eviction recovers synchronously
            # from its retained payload; an unstaged prefetch is a miss
            t.failed = True
            t.done.set()

    # ---- the transfer thread ----
    def _ensure_pool(self, leaves: Sequence[Any]) -> List[np.ndarray]:
        if self._pool is None:
            self._pool = [
                np.zeros((self.n_pages,) + tuple(a.shape[1:]),
                         _np_dtype(a)) for a in leaves]
        return self._pool

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=1.0)
                if self._stop:
                    return
                t = self._q.popleft()
            try:
                self._execute(t)
            except Exception:  # noqa: BLE001 — a failed transfer must
                # not kill the tier thread. A failed PREFETCH is just
                # a miss (nothing staged; the engine's fetch fallback
                # redoes it). A failed EVICTION marks the ticket so
                # fetch retries the copy from the retained device
                # payload — the host pool bytes are garbage until then
                # and must never be served as KV.
                t.failed = True
                import logging

                logging.getLogger(__name__).warning(
                    "kv host tier transfer failed", exc_info=True)
            finally:
                t.done.set()

    def _execute(self, t: _Ticket) -> None:
        t0 = time.monotonic()
        if t.kind == "evict":
            leaves = t.payload
            pool = None
            with self._lock:
                pool = self._ensure_pool(leaves)
            idx = np.asarray(t.host_ids, np.int64)
            moved = 0
            for buf, dev in zip(pool, leaves):
                arr = np.asarray(dev)  # the d2h sync — TIER thread only
                buf[idx] = arr
                moved += arr.nbytes
            t.payload = None  # release the gathered device arrays NOW:
            # the writers map holds this ticket until the host pages
            # free, and keeping the copies referenced would pin every
            # evicted page's bytes in HBM — the capacity the eviction
            # exists to reclaim
            self.stats.inc("kv_evictions_total", len(t.host_ids))
            self.stats.inc("kv_transfer_bytes_total", moved)
        else:  # prefetch: host -> device staging
            import jax.numpy as jnp

            with self._lock:
                if t.key not in self._want:
                    return  # the park this prefetch served is gone
                    # (seated / preempted / missed-and-fetched before
                    # the tier thread got here)
                ws = {id(w): w for h in t.host_ids
                      for w in (self._writers.get(h),)
                      if w is not None}
                if any(not w.done.is_set() or w.failed
                       for w in ws.values()):
                    # a not-yet-done writer was queued BEHIND this
                    # prefetch (FIFO: anything ahead already ran), so
                    # the pages were freed and reallocated — the key
                    # is stale, and waiting on that writer HERE would
                    # deadlock the only thread that can complete it.
                    # A failed writer needs fetch()'s recovery path.
                    # Either way skip: a prefetch is an overlap
                    # optimization, the unpark's own fetch covers it.
                    return
                pool = self._pool
                if pool is None:
                    return
                idx = np.asarray(t.host_ids, np.int64)
                leaves = [leaf[idx] for leaf in pool]
            staged = [jnp.asarray(a) for a in leaves]
            self.stats.inc("kv_transfer_bytes_total",
                           int(sum(a.nbytes for a in leaves)))
            with self._lock:
                if t.key in self._want:
                    self._staged[t.key] = (tuple(t.host_ids), staged, t)
        if self.observe_transfer is not None:
            try:
                self.observe_transfer(time.monotonic() - t0)
            except Exception:  # rafiki: noqa[silent-except] —
                pass           # observability must never kill transfers


def _np_dtype(a: Any) -> np.dtype:
    """Numpy dtype for a host mirror of a device leaf. bfloat16 has no
    numpy native dtype on some stacks; ml_dtypes (a jax dependency)
    provides it — np.asarray of a bf16 device array already yields it,
    so mirroring the reported dtype is exact."""
    return np.dtype(a.dtype)
