"""SLO classes, class-aware queueing, and the brownout ladder.

Rafiki's signature move is trading quality for latency under load
(SURVEY.md §3.3; the adaptive-gather controller is the unary half).
This module is the *mixed-traffic* half: overload becomes a first-class,
gracefully-degraded regime instead of an emergent FIFO stall.

Three pieces, deliberately host-side and dependency-free so both the
real :class:`~rafiki_tpu.serving.decode_engine.DecodeEngine` and the
chaos harness's stub engine run the SAME policy code:

- **SLO classes** (``interactive`` > ``batch`` > ``background``): a
  per-job default with a per-request override, plumbed predictor →
  scatter payload → worker → engine. :func:`normalize_slo` is the one
  validator every surface shares — the admin budget key, the HTTP
  body, the client SDK kwarg, and the engine must all mean the same
  three strings.

- :class:`ClassQueue` — per-class FIFO with **aging**: admission
  serves interactive first, FIFO within a class, and a class whose
  head has been skipped ``aging_skips`` times is force-promoted so
  background work never starves outright (bounded unfairness instead
  of unbounded wait). Caller-locked by design: both engines mutate it
  under their own admission lock, so the queue itself takes none.

- :class:`BrownoutController` — a hysteresis ladder over degradation
  stages driven by the live interactive latency p95: 0 *normal* → 1
  *capped* (best-effort admission caps halve) → 2 *clamped*
  (background ``max_new`` clamped) → 3 *paused* (background shed
  outright). Entering a stage needs ``dwell`` consecutive
  over-threshold observations and leaving needs ``dwell`` consecutive
  under-threshold ones, with distinct enter/exit ratios — load
  flapping around the target must not flap the ladder.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Deque, Dict, Optional, Tuple

#: priority order, highest first: admission serves interactive before
#: batch before background; preemption evicts in the reverse order.
SLO_CLASSES: Tuple[str, ...] = ("interactive", "batch", "background")

#: class -> rank (lower = more urgent); the comparison preemption and
#: admission both key on
SLO_PRIORITY: Dict[str, int] = {c: i for i, c in enumerate(SLO_CLASSES)}

DEFAULT_SLO = "interactive"

#: stage index -> operator-facing name (metrics expose the index; the
#: dashboard and /health show the name)
BROWNOUT_STAGES: Tuple[str, ...] = ("normal", "capped", "clamped",
                                    "paused")


def normalize_slo(value: Any, default: str = DEFAULT_SLO) -> str:
    """The one SLO-class validator every surface shares. ``None`` /
    empty → ``default``; anything else must (case-insensitively) name
    one of :data:`SLO_CLASSES` or ``ValueError`` — a typo'd class
    silently serving as interactive would defeat the whole admission
    policy."""
    if value is None:
        return default
    s = str(value).strip().lower()
    if not s:
        return default
    if s not in SLO_PRIORITY:
        raise ValueError(
            f"unknown SLO class {value!r} (one of: "
            f"{', '.join(SLO_CLASSES)})")
    return s


def slo_priority(slo: str) -> int:
    """Rank of a class (0 = most urgent). Unknown classes rank LAST —
    a duck-typed item with a bad label must never outrank real
    traffic."""
    return SLO_PRIORITY.get(slo, len(SLO_CLASSES))


def evictable_occupants(cls: str, occupants):
    """The occupants a ``cls`` head may preempt: strictly LOWER class,
    not shielded (aged promotions are immune). ``occupants`` is an
    iterable of ``(handle, slo, seq, shielded)``; returns the matching
    ``(handle, slo, seq)`` triples. This is THE eviction predicate —
    both the real decode engine's feasibility pre-check and every
    victim selection (real and stub) go through it, so the two can
    never drift apart (the paged reclaim loop's termination proof
    depends on feasibility and selection filtering identically)."""
    p = slo_priority(cls)
    return [(h, s, q) for h, s, q, shielded in occupants
            if not shielded and slo_priority(s) > p]


def preemption_victim(cls: str, occupants) -> Optional[Any]:
    """The ONE occupant to evict for a ``cls`` head: the YOUNGEST
    (highest seq) member of the LOWEST evictable class — least-urgent,
    least-invested work goes first. None when nothing ranks below
    ``cls`` (equal-or-higher-class work is never preempted)."""
    cands = evictable_occupants(cls, occupants)
    if not cands:
        return None
    return max(cands, key=lambda t: (slo_priority(t[1]), t[2]))[0]


class ClassQueue:
    """Per-class FIFO admission queue with starvation-bounding aging.

    NOT thread-safe on purpose: the decode engine mutates its queue
    under its own admission lock and the stub engine is single-threaded
    by contract; an internal lock here would nest under theirs for no
    benefit.

    Aging: every :meth:`pop` that serves class X increments a skip
    counter on every LOWER-priority class that had a waiter; a class
    whose counter reaches ``aging_skips`` is served next regardless of
    priority (and its counter resets). Interactive bursts therefore
    delay background by at most ``aging_skips`` admissions, never
    forever."""

    #: admissions a lower class may be skipped before force-promotion
    DEFAULT_AGING_SKIPS = 16

    def __init__(self, aging_skips: int = DEFAULT_AGING_SKIPS) -> None:
        self.aging_skips = max(1, int(aging_skips))
        self._qs: Dict[str, Deque[Any]] = {
            c: collections.deque() for c in SLO_CLASSES}
        self._skips: Dict[str, int] = {c: 0 for c in SLO_CLASSES}
        #: force-promotions performed (the aging mechanism firing) —
        #: engines surface it as the ``slo_aged_promotions`` gauge
        self.promotions = 0
        #: did the LAST pop fire the aging mechanism? Engines shield
        #: such admissions from preemption — an aged-promoted
        #: background request immediately evicted by the next
        #: interactive arrival would starve exactly the way aging
        #: exists to prevent
        self.last_pop_promoted = False

    def push(self, slo: str, item: Any, front: bool = False) -> None:
        """Enqueue ``item`` under ``slo`` (validated). ``front``
        re-queues a preempted item ahead of its class peers so it
        resumes before newer same-class work."""
        q = self._qs[normalize_slo(slo)]
        if front:
            q.appendleft(item)
        else:
            q.append(item)

    def __len__(self) -> int:
        return sum(len(q) for q in self._qs.values())

    def __bool__(self) -> bool:
        return any(self._qs.values())

    def depth(self, slo: str) -> int:
        return len(self._qs[normalize_slo(slo)])

    def depths(self) -> Dict[str, int]:
        return {c: len(q) for c, q in self._qs.items()}

    def next_class(self) -> Optional[str]:
        """The class the next :meth:`pop` will serve: an aged class
        first (most-skipped wins ties), else the highest-priority
        non-empty one. None when empty."""
        aged = [c for c in SLO_CLASSES
                if self._qs[c] and self._skips[c] >= self.aging_skips]
        if aged:
            return max(aged, key=lambda c: self._skips[c])
        for c in SLO_CLASSES:
            if self._qs[c]:
                return c
        return None

    def peek(self) -> Optional[Tuple[str, Any]]:
        """(class, head item) the next pop would return, without
        popping — engines check page reservations against the head
        before committing."""
        c = self.next_class()
        if c is None:
            return None
        return c, self._qs[c][0]

    def pop(self) -> Optional[Tuple[str, Any]]:
        """Serve the next item (see :meth:`next_class`), updating the
        aging counters."""
        c = self.next_class()
        if c is None:
            return None
        self.last_pop_promoted = bool(
            self._skips[c] >= self.aging_skips and any(
                self._qs[h] for h in SLO_CLASSES
                if SLO_PRIORITY[h] < SLO_PRIORITY[c]))
        if self.last_pop_promoted:
            # served ahead of waiting higher-priority work: the aging
            # mechanism fired, not ordinary priority order
            self.promotions += 1
        item = self._qs[c].popleft()
        self._skips[c] = 0
        for lower in SLO_CLASSES:
            if SLO_PRIORITY[lower] > SLO_PRIORITY[c] and self._qs[lower]:
                self._skips[lower] += 1
        return c, item

    def clear(self) -> None:
        for c in SLO_CLASSES:
            self._qs[c].clear()
            self._skips[c] = 0


class BrownoutController:
    """Hysteresis ladder over degradation stages, fed by the live
    interactive latency p95.

    Stages (index is the ``brownout_stage`` gauge):

    0. **normal** — no degradation.
    1. **capped** — best-effort (batch + background) shed caps halve.
    2. **clamped** — background ``max_new`` additionally clamped to
       ``clamp_max_new`` (long best-effort generations release their
       slots/pages sooner).
    3. **paused** — background is shed outright (structured 503 with
       ``retry_after_s``); batch keeps the halved cap.

    A stage is entered only after ``dwell`` CONSECUTIVE observations
    above ``target_p95_s × enter_ratio`` and left only after ``dwell``
    consecutive observations below ``target_p95_s × exit_ratio``
    (enter > exit: the band between them is sticky, so p95 noise
    around the target cannot flap the ladder). ``target_p95_s <= 0``
    disables the ladder (stage pinned at 0) — shedding then runs on
    the static depth caps alone."""

    def __init__(self, target_p95_s: float = 0.0,
                 enter_ratio: float = 1.5, exit_ratio: float = 1.1,
                 dwell: int = 3) -> None:
        self.target_p95_s = float(target_p95_s)
        self.enter_ratio = max(1.0, float(enter_ratio))
        self.exit_ratio = max(0.0, min(float(exit_ratio),
                                       self.enter_ratio))
        self.dwell = max(1, int(dwell))
        # observe() runs on whatever request thread refreshed the load
        # snapshot, and several can race: streak counters and the stage
        # ladder mutate under the lock, readers come through the stage
        # property
        self._lock = threading.Lock()
        self._stage = 0
        self.escalations = 0
        self.deescalations = 0
        self._hot = 0
        self._cool = 0
        self._last_p95 = 0.0

    @property
    def enabled(self) -> bool:
        return self.target_p95_s > 0

    @property
    def stage(self) -> int:
        with self._lock:
            return self._stage

    @stage.setter
    def stage(self, value: int) -> None:
        # operator/test override: pin the ladder at a stage
        with self._lock:
            self._stage = value

    def observe(self, p95_s: Optional[float]) -> int:
        """Feed one interactive-p95 observation; returns the (possibly
        changed) stage. ``None``/non-positive observations (no
        interactive traffic yet) count toward COOLING — an idle fleet
        must walk back down the ladder, not stick at a stale stage."""
        if not self.enabled:
            return self.stage
        v = float(p95_s) if isinstance(p95_s, (int, float)) and \
            not isinstance(p95_s, bool) else 0.0
        with self._lock:
            self._last_p95 = v
            if v > self.target_p95_s * self.enter_ratio:
                self._hot += 1
                self._cool = 0
                if self._hot >= self.dwell and \
                        self._stage < len(BROWNOUT_STAGES) - 1:
                    self._stage += 1
                    self.escalations += 1
                    self._hot = 0
            elif v < self.target_p95_s * self.exit_ratio:
                self._cool += 1
                self._hot = 0
                if self._cool >= self.dwell and self._stage > 0:
                    self._stage -= 1
                    self.deescalations += 1
                    self._cool = 0
            else:
                # the sticky band between exit and enter: neither
                # streak survives it — transitions need consecutive
                # evidence
                self._hot = 0
                self._cool = 0
            return self._stage

    # ---- what each stage means for admission (shared semantics:
    # ---- predictor shed gate and docs both read these) ----
    def shed_cap(self, slo: str, base_cap: int) -> int:
        """The effective queue-depth cap for ``slo`` at the current
        stage: interactive is never capped, best-effort caps halve at
        stage >= 1, background drops to 0 (pause) at stage 3."""
        if slo == "interactive":
            return -1  # sentinel: no cap
        stage = self.stage
        cap = max(0, int(base_cap))
        if stage >= 1 and cap > 1:
            # halve, floored at 1 — but an operator cap of 0 or 1
            # stays put: the ladder may only TIGHTEN admission, never
            # raise a stricter configured cap
            cap = max(1, cap // 2)
        if slo == "background" and stage >= 3:
            cap = 0
        return cap

    def clamp_max_new(self, slo: str, requested: Optional[int],
                      clamp: int) -> Optional[int]:
        """Stage >= 2: background generations are clamped to ``clamp``
        new tokens (shorter holds on slots/pages). Other classes and
        lower stages pass through."""
        if self.stage >= 2 and slo == "background" and clamp > 0:
            return clamp if not requested else min(int(requested), clamp)
        return requested

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"stage": self._stage,
                    "stage_name": BROWNOUT_STAGES[self._stage],
                    "target_p95_s": self.target_p95_s,
                    "enabled": self.enabled,
                    "last_interactive_p95_s": round(self._last_p95, 4),
                    "escalations": self.escalations,
                    "deescalations": self.deescalations}
