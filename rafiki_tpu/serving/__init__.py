"""Online serving: queues, predictor endpoint, ensembling."""

from .queues import (InProcQueueHub, KVQueueHub, QueueHub, pack_message,
                     unpack_message)

__all__ = ["QueueHub", "InProcQueueHub", "KVQueueHub", "pack_message",
           "unpack_message"]
