"""Online serving: queues, predictor endpoint, ensembling, routing."""

from .queues import (InProcQueueHub, KVQueueHub, QueueHub, pack_message,
                     unpack_message)
from .router import Router

__all__ = ["QueueHub", "InProcQueueHub", "KVQueueHub", "Router",
           "pack_message", "unpack_message"]
