"""System-wide enums and constants.

Mirrors the reference's ``rafiki/constants.py`` surface (BudgetOption,
job/trial statuses, service & user types) — see SURVEY.md §2 "Constants".
String-valued enums so they serialize cleanly through JSON/SQLite.
"""

from __future__ import annotations

import enum


class StrEnum(str, enum.Enum):
    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.value


class BudgetOption(StrEnum):
    """Budget knobs accepted by ``create_train_job``."""

    TRIAL_COUNT = "TRIAL_COUNT"
    TIME_HOURS = "TIME_HOURS"
    # Reference budgets GPUs; here the unit is TPU sub-meshes (worker slots).
    WORKER_COUNT = "WORKER_COUNT"
    # Accepted alias for reference compatibility.
    GPU_COUNT = "GPU_COUNT"


class TrainJobStatus(StrEnum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class SubTrainJobStatus(StrEnum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class TrialStatus(StrEnum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    ERRORED = "ERRORED"
    TERMINATED = "TERMINATED"  # killed early (e.g. BOHB rung cut / preemption)


class InferenceJobStatus(StrEnum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class ServiceType(StrEnum):
    ADVISOR = "ADVISOR"
    TRAIN_WORKER = "TRAIN_WORKER"
    INFERENCE_WORKER = "INFERENCE_WORKER"
    PREDICTOR = "PREDICTOR"
    DATA_PLANE = "DATA_PLANE"  # native kv/queue server (Redis stand-in)


class ServiceStatus(StrEnum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"
    # verdict of the boot reconciler: the row's recorded process did not
    # survive the admin's death (pid gone, identity mismatch, or failed
    # health probe). Terminal like ERRORED; crashed WORKERS of a still-
    # RUNNING job flow into the respawn path.
    CRASHED = "CRASHED"


class UserType(StrEnum):
    SUPERADMIN = "SUPERADMIN"
    ADMIN = "ADMIN"
    MODEL_DEVELOPER = "MODEL_DEVELOPER"
    APP_DEVELOPER = "APP_DEVELOPER"


class TaskType(StrEnum):
    """Well-known task names; model templates declare which they serve."""

    IMAGE_CLASSIFICATION = "IMAGE_CLASSIFICATION"
    TEXT_CLASSIFICATION = "TEXT_CLASSIFICATION"
    POS_TAGGING = "POS_TAGGING"
    TABULAR_CLASSIFICATION = "TABULAR_CLASSIFICATION"
    TABULAR_REGRESSION = "TABULAR_REGRESSION"
    LANGUAGE_MODELING = "LANGUAGE_MODELING"


class ModelAccessRight(StrEnum):
    PUBLIC = "PUBLIC"
    PRIVATE = "PRIVATE"


class ModelDependencyManagedBy(StrEnum):
    """Reference installs pip deps per model container; here deps must be
    preinstalled (no egress), so this only records intent."""

    REQUESTED = "REQUESTED"
    PREINSTALLED = "PREINSTALLED"
