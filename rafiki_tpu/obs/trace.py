"""Request tracing: trace IDs + a bounded per-process span ring.

One trace ID is minted at the predictor (or honored from an inbound
``X-Rafiki-Trace-Id`` header), rides in the scatter payload to the
workers, and every process appends its own span records — queued,
admitted, prefill, per-N decode-step marks, first_token,
done/expired/preempted — into its local :class:`TraceBuffer`. Each
service exposes its buffer as ``GET /debug/requests?n=K``; joining the
outputs on the trace ID answers "where did this request's 900 ms go?"
across predictor and worker without any central collector.

Timestamps are **monotonic process uptime seconds** (``uptime_s`` at
record level, ``t`` per span): durations within one process are exact,
wall-clock steps can't corrupt them, and cross-process alignment happens
by trace ID, not by clock.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

#: inbound trace ids are untrusted header bytes: bound the length and
#: alphabet so a hostile client can't stuff the ring with megabyte ids
_TRACE_ID_OK = set("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_.:-")
_TRACE_ID_MAX = 128


def mint_trace_id() -> str:
    return uuid.uuid4().hex


def sanitize_trace_id(trace_id: Optional[str]) -> str:
    """A safe trace id: the inbound one when it is well-formed, else
    empty (caller mints). Never raises — a garbage header must degrade
    to a fresh id, not 500 the request."""
    if not isinstance(trace_id, str):
        return ""
    tid = trace_id.strip()
    if not tid or len(tid) > _TRACE_ID_MAX or \
            any(c not in _TRACE_ID_OK for c in tid):
        return ""
    return tid


class TraceBuffer:
    """Bounded ring of request trace records (newest win; churn evicts
    oldest). O(1) span append via a trace-id index; every read returns
    JSON-safe copies so HTTP handlers never alias live mutable state."""

    def __init__(self, maxlen: int = 256) -> None:
        self.maxlen = max(1, int(maxlen))
        self._lock = threading.Lock()
        self._ring: "collections.deque[Dict[str, Any]]" = \
            collections.deque()
        self._index: Dict[str, Dict[str, Any]] = {}
        self._t0 = time.monotonic()

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def start(self, trace_id: str, request_id: str = "",
              span: str = "queued", **attrs: Any) -> str:
        """Open a record for ``trace_id`` with its first span. Returns
        the trace id (convenience for ``start(mint_trace_id(), ...)``
        call sites)."""
        now = self._now()
        rec = {"trace_id": str(trace_id),
               "request_id": str(request_id),
               "uptime_s": now,
               "spans": [dict(attrs, name=span, t=now)]}
        with self._lock:
            if len(self._ring) >= self.maxlen:
                old = self._ring.popleft()
                # only unindex if the slot still points at the evictee
                if self._index.get(old["trace_id"]) is old:
                    del self._index[old["trace_id"]]
            self._ring.append(rec)
            self._index[rec["trace_id"]] = rec
        return rec["trace_id"]

    def add_span(self, trace_id: str, name: str, **attrs: Any) -> None:
        """Append a span to ``trace_id``'s record, creating the record
        if it was evicted (late spans under churn must not be lost —
        a fragment beats nothing when debugging)."""
        with self._lock:
            rec = self._index.get(str(trace_id))
        if rec is None:
            self.start(str(trace_id), span=name, **attrs)
            return
        span = dict(attrs, name=name, t=self._now())
        with self._lock:
            rec["spans"].append(span)

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._index.get(str(trace_id))
            return None if rec is None else _copy(rec)

    def recent(self, n: int = 32) -> List[Dict[str, Any]]:
        """The most recent ``n`` records, newest first (the
        ``/debug/requests`` payload)."""
        n = max(0, int(n))
        with self._lock:
            tail = list(self._ring)[-n:] if n else []
        return [_copy(r) for r in reversed(tail)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def _copy(rec: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(rec)
    out["spans"] = [dict(s) for s in rec["spans"]]
    return out
