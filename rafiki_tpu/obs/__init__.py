"""rafiki-tpu observability plane (dependency-free).

One metrics core (counters / gauges / fixed-bucket histograms /
StatsMaps + Prometheus text exposition), one request-tracing core
(trace IDs + bounded span rings), and the HTTP surfacing that mounts
``GET /metrics`` and ``GET /debug/requests`` on every service. See
``docs/observability.md`` for the metric catalog and how the pieces
join across processes.
"""

from .http import DEBUG_REQUESTS_DEFAULT_N, ObsServer, mount_obs_routes
from .metrics import (DEFAULT_LATENCY_BUCKETS_S, PROM_CONTENT_TYPE,
                      Counter, Gauge, Histogram, MetricsRegistry,
                      StatsMap)
from .trace import TraceBuffer, mint_trace_id, sanitize_trace_id

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "StatsMap",
    "DEFAULT_LATENCY_BUCKETS_S", "PROM_CONTENT_TYPE",
    "TraceBuffer", "mint_trace_id", "sanitize_trace_id",
    "ObsServer", "mount_obs_routes", "DEBUG_REQUESTS_DEFAULT_N",
]
