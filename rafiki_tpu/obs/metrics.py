"""Process-local metrics plane: counters, gauges, histograms, stats maps.

The repo's control loops — the predictor's adaptive gather, the paged-KV
admission backpressure, and the planned router/SLO controllers (ROADMAP
items 1 and 5) — all feed on serving signals, and until this module the
signals were hand-rolled dicts pushed around ad hoc. This is the one
metrics core every service shares:

- **Lock-cheap**: one mutex per instrument, O(1) ``inc``/``observe``
  (bucket lookup is a bisect over a dozen bounds), no percentile scan
  anywhere near a hot path. Quantiles are derived from fixed histogram
  buckets only when someone asks (a /metrics scrape, a /health render).
- **Dependency-free**: stdlib only — this package must be importable by
  every process in the stack, including ones pinned off the accelerator.
- **Prometheus text** (exposition format 0.0.4) via
  :meth:`MetricsRegistry.render_prometheus`, mounted as ``GET /metrics``
  on every HTTP surface (``rafiki_tpu.obs.http``).
- **StatsMap** replaces the hand-rolled ``self.stats`` dicts (decode
  engine, workers): a locked dict with ``inc``/``set``/``max_set`` and a
  race-free ``snapshot()`` — existing gauge names (``kv_pages_used``,
  ``admission_stalls``, ``dropped_expired``, …) keep their names, so
  dashboards and tests migrate mechanically. The
  ``obs-unregistered-metric`` lint rule keeps new counters from
  regressing to bare ``self.stats[...] = ...`` writes.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import (Any, Callable, Dict, Iterator, List, Mapping,
                    MutableMapping, Optional, Sequence, Tuple)

#: default latency buckets (seconds): sub-ms to minutes, roughly
#: log-spaced — TTFT, queue wait, and end-to-end request latency all
#: land usefully inside this range on both CPU fallback and TPU.
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

#: Prometheus text exposition content type (version 0.0.4)
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt_value(v: Any) -> str:
    """A sample value in exposition form (ints stay ints; floats use
    repr, which round-trips; non-numeric values are dropped upstream)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _fmt_labels(labels: Optional[Mapping[str, str]],
                extra: Optional[Mapping[str, str]] = None) -> str:
    items: List[Tuple[str, str]] = []
    for src in (labels, extra):
        if src:
            items.extend(src.items())
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in items)
    return "{" + body + "}"


class Counter:
    """Monotonic counter. ``inc`` returns the new value so callers that
    also need the running total (the worker's drop logging) read it from
    the same locked update instead of a second round-trip."""

    kind = "counter"

    __slots__ = ("name", "help", "labels", "_lock", "_v")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Mapping[str, str]] = None) -> None:
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n: float = 1.0) -> float:
        with self._lock:
            self._v += n
            return self._v

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def expose(self) -> List[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self.value)}"]

    def snapshot_items(self) -> List[Tuple[str, float]]:
        return [(self.name, self.value)]


class Gauge:
    """Settable value; ``fn`` makes it a live gauge evaluated at read
    time (the admin exposes service/slot counts this way — no second
    bookkeeping next to the source of truth)."""

    kind = "gauge"

    __slots__ = ("name", "help", "labels", "fn", "_lock", "_v")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Mapping[str, str]] = None,
                 fn: Optional[Callable[[], float]] = None) -> None:
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.fn = fn
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:  # noqa: BLE001 — a scrape must degrade to
                return float("nan")  # NaN, never 500 the surface
        with self._lock:
            return self._v

    def expose(self) -> List[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self.value)}"]

    def snapshot_items(self) -> List[Tuple[str, float]]:
        return [(self.name, self.value)]


class Histogram:
    """Fixed-bucket histogram: O(log n_buckets) observe under one
    mutex, cumulative Prometheus exposition, and bucket-interpolated
    quantiles computed only on demand (dashboard p50/p95) — never a
    sorted-sample scan on the request path."""

    kind = "histogram"

    __slots__ = ("name", "help", "labels", "buckets", "_lock", "_counts",
                 "_sum", "_n")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                 labels: Optional[Mapping[str, str]] = None) -> None:
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"histogram {name!r} needs >= 1 bucket")
        if any(b != b or math.isinf(b) for b in bs):
            raise ValueError(f"histogram {name!r}: finite buckets only "
                             "(+Inf is implicit)")
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.buckets = tuple(bs)
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)  # [+Inf] is the last slot
        self._sum = 0.0
        self._n = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # le semantics: v lands in the first bucket whose bound >= v
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, p: float) -> float:
        """Bucket-interpolated quantile estimate in [first bound's
        lower edge (0), last finite bound]. Coarse by construction —
        the fidelity of fixed buckets — but monotone in ``p`` and
        cheap enough for every dashboard refresh."""
        with self._lock:
            counts = list(self._counts)
            n = self._n
        if n == 0:
            return 0.0
        target = max(1, math.ceil(min(1.0, max(0.0, p)) * n))
        cum = 0
        lo = 0.0
        for i, hi in enumerate(self.buckets):
            c = counts[i]
            if cum + c >= target:
                return lo + (hi - lo) * ((target - cum) / c)
            cum += c
            lo = hi
        return self.buckets[-1]  # target lives in the +Inf bucket

    def expose(self) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total = self._n
            s = self._sum
        lines: List[str] = []
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += counts[i]
            lines.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(self.labels, {'le': _fmt_value(b)})} "
                f"{cum}")
        lines.append(f"{self.name}_bucket"
                     f"{_fmt_labels(self.labels, {'le': '+Inf'})} "
                     f"{total}")
        lines.append(f"{self.name}_sum{_fmt_labels(self.labels)} "
                     f"{_fmt_value(s)}")
        lines.append(f"{self.name}_count{_fmt_labels(self.labels)} "
                     f"{total}")
        return lines

    def snapshot_items(self) -> List[Tuple[str, float]]:
        with self._lock:
            total, s = self._n, self._sum
        return [(f"{self.name}_count", total), (f"{self.name}_sum", s)]


class StatsMap(MutableMapping):
    """A locked dict of numeric counters/gauges with a race-free
    snapshot — the registry-native replacement for the hand-rolled
    ``self.stats`` dicts.

    Reads keep dict ergonomics (``stats["steps"]``, ``dict(stats)``,
    iteration) so every existing test and bench stage works unchanged;
    writes go through :meth:`inc`/:meth:`set`/:meth:`max_set` so the
    ``obs-unregistered-metric`` lint rule can police bare
    ``stats[...] = ...`` writes out of the repo. Iteration and
    :meth:`snapshot` copy under the lock, which is the whole point:
    publishing a snapshot can never race a concurrent mutation into a
    ``dictionary changed size during iteration`` crash (the bug
    ``InferenceWorker._publish_stats`` used to carry).
    """

    def __init__(self, initial: Optional[Mapping[str, Any]] = None
                 ) -> None:
        self._lock = threading.Lock()
        self._d: Dict[str, Any] = dict(initial or {})

    # ---- the write API ----
    def inc(self, key: str, n: float = 1) -> float:
        with self._lock:
            v = self._d.get(key, 0) + n
            self._d[key] = v
            return v

    def set(self, key: str, v: Any) -> None:
        with self._lock:
            self._d[key] = v

    def max_set(self, key: str, v: Any) -> None:
        """Keep the running maximum (high-water marks)."""
        with self._lock:
            self._d[key] = max(self._d.get(key, v), v)

    def reset(self, keep: Optional[Mapping[str, Any]] = None) -> None:
        """Zero every key in place (the key set survives — gauges keep
        exposing), then overlay ``keep`` (capacity gauges that describe
        configuration, not traffic)."""
        with self._lock:
            for k in self._d:
                self._d[k] = 0
            if keep:
                self._d.update(keep)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._d)

    # ---- Mapping protocol (reads + duck-typed compat) ----
    def __getitem__(self, key: str) -> Any:
        with self._lock:
            return self._d[key]

    def __setitem__(self, key: str, v: Any) -> None:
        # exists for duck-typed engine compatibility only; repo code
        # uses inc/set (the lint rule flags subscript writes)
        self.set(key, v)

    def __delitem__(self, key: str) -> None:
        with self._lock:
            del self._d[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self.snapshot())

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def __repr__(self) -> str:
        return f"StatsMap({self.snapshot()!r})"


_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or any(c not in _NAME_OK
                                            for c in name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


class MetricsRegistry:
    """Get-or-create instrument registry for one process.

    ``snapshot()`` flattens everything into a plain name→value dict
    (what workers publish to the hub); ``render_prometheus()`` is the
    ``GET /metrics`` body. Registered :class:`StatsMap`s (or any
    zero-arg callable returning a dict) are merged into both as
    untyped gauges — that is how the decode engine's counters surface
    without the engine knowing about HTTP.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                Any] = {}
        self._collectors: List[Tuple[str,
                                     Callable[[], Mapping[str, Any]]]] = []

    # ---- get-or-create ----
    def _get(self, cls, name: str, help: str,
             labels: Optional[Mapping[str, str]], **kw: Any):
        key = (_check_name(name),
               tuple(sorted((labels or {}).items())))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, help, labels=labels, **kw)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}")
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Mapping[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Mapping[str, str]] = None,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get(Gauge, name, help, labels, fn=fn)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                  labels: Optional[Mapping[str, str]] = None
                  ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def register_stats(self, stats: Any, prefix: str = "") -> None:
        """Merge a :class:`StatsMap` (or zero-arg dict callable) into
        snapshots and exposition, optionally name-prefixed."""
        fn = stats.snapshot if hasattr(stats, "snapshot") else stats
        if not callable(fn):
            raise TypeError("register_stats wants a StatsMap or a "
                            "zero-arg callable returning a dict")
        with self._lock:
            self._collectors.append((prefix, fn))

    # ---- read-out ----
    def _parts(self):
        with self._lock:
            return list(self._instruments.values()), \
                list(self._collectors)

    def snapshot(self) -> Dict[str, Any]:
        """Flat name→value view: counters/gauges by name, histograms as
        ``<name>_count``/``<name>_sum``, collectors merged (prefixed).
        First registration wins on a name collision."""
        instruments, collectors = self._parts()
        out: Dict[str, Any] = {}
        for inst in instruments:
            for k, v in inst.snapshot_items():
                out.setdefault(k, v)
        for prefix, fn in collectors:
            try:
                d = fn()
            except Exception:  # rafiki: noqa[silent-except] — one
                continue  # broken collector must not take the whole
                # snapshot down, and logging per scrape would flood
            for k, v in d.items():
                out.setdefault(f"{prefix}{k}", v)
        return out

    def render_prometheus(self) -> str:
        """The ``GET /metrics`` body (text exposition format 0.0.4)."""
        instruments, collectors = self._parts()
        lines: List[str] = []
        seen: set = set()
        for inst in instruments:
            if inst.name not in seen:
                seen.add(inst.name)
                if inst.help:
                    lines.append(f"# HELP {inst.name} {inst.help}")
                lines.append(f"# TYPE {inst.name} {inst.kind}")
            lines.extend(inst.expose())
        for prefix, fn in collectors:
            try:
                d = fn()
            except Exception:  # rafiki: noqa[silent-except] — a scrape
                continue  # must render what it can, not 500; per-scrape
                # logging of a persistently broken collector would flood
            for k in sorted(d):
                v = d[k]
                if not isinstance(v, (int, float)):
                    continue  # exposition is numeric-only
                name = f"{prefix}{k}"
                if any(c not in _NAME_OK for c in name) or \
                        name in seen:
                    continue
                seen.add(name)
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"
