"""HTTP surfacing for the obs plane: ``/metrics`` + ``/debug/requests``.

Two entry points:

- :func:`mount_obs_routes` adds the two routes to an EXISTING
  :class:`~rafiki_tpu.utils.http.JsonHttpService` (admin app, predictor
  service — processes that already listen).
- :class:`ObsServer` is a standalone single-purpose server for
  processes that had no HTTP surface at all (the inference and train
  workers): the worker loop stays a queue consumer; scrapes and
  timeline pulls ride a daemon-threaded sidecar on an ephemeral port.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ..utils.http import JsonHttpService, RawResponse
from .metrics import PROM_CONTENT_TYPE, MetricsRegistry
from .trace import TraceBuffer

#: default /debug/requests page size (override with ?n=K)
DEBUG_REQUESTS_DEFAULT_N = 32


def mount_obs_routes(http: JsonHttpService, registry: MetricsRegistry,
                     traces: Optional[TraceBuffer] = None) -> None:
    """Mount ``GET /metrics`` (Prometheus text) and
    ``GET /debug/requests?n=K`` (JSON trace records, newest first)."""

    def _metrics(_m, _b, _h) -> Tuple[int, Any]:
        return 200, RawResponse(
            registry.render_prometheus().encode("utf-8"),
            PROM_CONTENT_TYPE)

    def _debug_requests(m, _b, _h) -> Tuple[int, Any]:
        try:
            n = int(m.get("n", DEBUG_REQUESTS_DEFAULT_N))
        except (TypeError, ValueError):
            return 400, {"error": "n must be an integer"}
        if n < 0:
            return 400, {"error": "n must be >= 0"}
        recs = traces.recent(n) if traces is not None else []
        return 200, {"requests": recs, "count": len(recs)}

    http.route("GET", "/metrics", _metrics)
    http.route("GET", "/debug/requests", _debug_requests)


class ObsServer:
    """Sidecar observability endpoint for HTTP-less processes.

    Serves exactly ``/metrics``, ``/debug/requests``, and a trivial
    ``/health`` on a daemon-threaded stdlib server; the owning loop
    never blocks on it and ``stop()`` is idempotent.
    """

    def __init__(self, registry: MetricsRegistry,
                 traces: Optional[TraceBuffer] = None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.registry = registry
        self.traces = traces
        # the sidecar instruments its own scrapes too (http_requests_
        # total on a worker IS the scrape count — a cheap liveness probe)
        self.http = JsonHttpService(host, port, registry=registry)
        mount_obs_routes(self.http, registry, traces)
        self.http.route("GET", "/health",
                        lambda _m, _b, _h: (200, {"ok": True}))
        self._started = False

    def start(self) -> Tuple[str, int]:
        host, port = self.http.start()
        self._started = True
        return host, port

    @property
    def port(self) -> int:
        return self.http.port

    def stop(self) -> None:
        if self._started:
            self.http.stop()
            self._started = False
