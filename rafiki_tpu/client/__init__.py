"""User-facing SDK mirroring the reference's ``rafiki.client``."""

from .client import Client

__all__ = ["Client"]
