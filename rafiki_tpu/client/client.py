"""Client SDK: the user-facing mirror of the Admin REST API.

Parity target: the reference's ``rafiki/client/client.py`` ``Client``
surface (SURVEY.md §2 "Client SDK", §1 layer 2): ``login``,
``create_model``, ``create_dataset``, ``create_train_job``,
``get_train_job``, ``get_best_trials_of_train_job``,
``create_inference_job``, and a ``predict`` helper against the deployed
predictor endpoint.
"""

from __future__ import annotations

import base64
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Type

from ..utils.http import HttpStatusError, json_request

#: cap on how long predict() sleeps honoring a 503's retry_after_s —
#: a server bug must not park the caller for an hour
MAX_RETRY_AFTER_S = 30.0


@dataclass
class StreamInterrupted:
    """Typed terminal event for a stream that ended with a *resumable*
    error: the predictor lost every healthy worker mid-stream and hands
    back the query id plus the text delivered so far. Pass ``partial``
    back as ``resume=`` (or let ``predict_stream(auto_resume=...)`` do
    it) to continue the stream without re-paying the delivered tokens.

    Duck-dict compatible (``ev.get("done")``, ``ev["error"]``) so event
    loops written against plain dict events keep working."""

    error: str
    partial: List[Optional[str]]
    qid: str = ""
    trace_id: str = ""
    retry_after_s: float = 0.0
    raw: Dict[str, Any] = field(default_factory=dict)
    done: bool = True
    resumable: bool = True

    def get(self, key: str, default: Any = None) -> Any:
        return self.raw.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self.raw[key]

    def __contains__(self, key: str) -> bool:
        return key in self.raw


class Client:
    def __init__(self, admin_url: str = "http://127.0.0.1:3000",
                 timeout: float = 120.0) -> None:
        self.admin_url = admin_url.rstrip("/")
        self.timeout = timeout
        self._token: Optional[str] = None

    # ---- plumbing ----
    def _call(self, method: str, path: str,
              body: Any = None) -> Any:
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        return json_request(method, f"{self.admin_url}{path}", body,
                            headers=headers, timeout=self.timeout)

    # ---- auth ----
    def login(self, email: str, password: str) -> Dict[str, Any]:
        out = json_request("POST", f"{self.admin_url}/tokens",
                           {"email": email, "password": password},
                           timeout=self.timeout)
        self._token = out["token"]
        return out

    def create_user(self, email: str, password: str,
                    user_type: str = "APP_DEVELOPER") -> Dict[str, Any]:
        return self._call("POST", "/users",
                          {"email": email, "password": password,
                           "user_type": user_type})

    # ---- models ----
    def create_model(self, name: str, task: str, model_class: Any,
                     access_right: str = "PRIVATE") -> Dict[str, Any]:
        """``model_class`` may be a BaseModel subclass (its module source
        is shipped) or raw source bytes + ``name:class`` string."""
        if isinstance(model_class, (bytes, bytearray)):
            raise TypeError("pass (bytes, class_name) via create_model_raw")
        from ..model.base import serialize_model_class

        model_bytes = serialize_model_class(model_class)
        return self.create_model_raw(name, task, model_class.__name__,
                                     model_bytes, access_right)

    def create_model_raw(self, name: str, task: str, class_name: str,
                         model_bytes: bytes,
                         access_right: str = "PRIVATE") -> Dict[str, Any]:
        return self._call("POST", "/models", {
            "name": name, "task": task, "model_class": class_name,
            "model_bytes": base64.b64encode(model_bytes).decode(),
            "access_right": access_right})

    def get_models(self, task: Optional[str] = None) -> List[Dict]:
        out = self._call("GET", "/models")
        return [m for m in out if task is None or m["task"] == task]

    # ---- datasets ----
    def create_dataset(self, name: str, task: str,
                       uri: str) -> Dict[str, Any]:
        return self._call("POST", "/datasets",
                          {"name": name, "task": task, "uri": uri})

    # ---- train jobs ----
    def create_train_job(self, app: str, task: str, train_dataset_id: str,
                         val_dataset_id: str,
                         budget: Optional[Dict[str, Any]] = None,
                         model_ids: Optional[List[str]] = None,
                         train_args: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        return self._call("POST", "/train_jobs", {
            "app": app, "task": task,
            "train_dataset_id": train_dataset_id,
            "val_dataset_id": val_dataset_id,
            "budget": budget or {"TRIAL_COUNT": 5},
            "model_ids": model_ids, "train_args": train_args})

    def get_train_job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/train_jobs/{job_id}")

    def get_train_job_of_app(self, app: str) -> Dict[str, Any]:
        return self._call("GET", f"/train_jobs/app/{app}")

    def stop_train_job(self, job_id: str) -> None:
        self._call("POST", f"/train_jobs/{job_id}/stop")

    def wait_until_train_job_finished(self, job_id: str,
                                      timeout: float = 1800.0,
                                      poll_s: float = 1.0
                                      ) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.get_train_job(job_id)
            if job["status"] in ("STOPPED", "ERRORED"):
                return job
            time.sleep(poll_s)
        raise TimeoutError(f"train job {job_id} still running")

    def get_trials_of_train_job(self, job_id: str) -> List[Dict]:
        return self._call("GET", f"/train_jobs/{job_id}/trials")

    def get_best_trials_of_train_job(self, job_id: str,
                                     max_count: int = 2) -> List[Dict]:
        return self._call("GET", f"/train_jobs/{job_id}/best_trials",
                          {"max_count": max_count})

    def get_trial_logs(self, trial_id: str) -> List[Dict]:
        return self._call("GET", f"/trials/{trial_id}/logs")

    # ---- inference jobs ----
    def create_inference_job(self, train_job_id: str,
                             max_workers: int = 2,
                             budget: Optional[Dict[str, Any]] = None
                             ) -> Dict[str, Any]:
        """``budget={"MULTI_ADAPTER": 1}`` deploys the best-N LM trials
        as ONE stacked-adapter worker (route requests with
        ``sampling={"adapter_id": i}``, i = i-th best trial) instead of
        N full replicas."""
        body: Dict[str, Any] = {"train_job_id": train_job_id,
                                "max_workers": max_workers}
        if budget:
            body["budget"] = budget
        return self._call("POST", "/inference_jobs", body)

    def get_inference_job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/inference_jobs/{job_id}")

    def get_inference_job_health(self, job_id: str) -> Dict[str, Any]:
        """The predictor's live ``/health`` (req/s, latency
        percentiles, per-worker engine/drop counters), proxied through
        the admin — the dashboard's data source, usable from scripts."""
        return self._call("GET", f"/inference_jobs/{job_id}/health")

    def stop_inference_job(self, job_id: str) -> None:
        self._call("POST", f"/inference_jobs/{job_id}/stop")

    def rolling_restart_inference_job(self, job_id: str,
                                      drain_timeout: float = 120.0,
                                      expected_workers: int = 2
                                      ) -> Dict[str, Any]:
        """Cycle the job's workers one at a time with graceful drain —
        a deploy/restart that never drops a stream. Returns the
        old→new service id pairs. The endpoint is synchronous and can
        legitimately block ~``expected_workers × drain_timeout`` while
        long streams finish, so the socket timeout is sized to that
        (plus respawn slack) instead of the unary default — a
        premature client timeout would tempt a retry the server
        rejects with 409 (one rolling restart at a time)."""
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        sock = max(self.timeout,
                   max(1, int(expected_workers)) * drain_timeout + 60.0)
        return json_request(
            "POST",
            f"{self.admin_url}/inference_jobs/{job_id}/rolling_restart",
            {"drain_timeout": drain_timeout}, headers=headers,
            timeout=sock)

    def scale_inference_job(self, job_id: str, workers: int,
                            drain_timeout: float = 120.0
                            ) -> Dict[str, Any]:
        """Manually scale the job's worker pool to exactly ``workers``
        replicas: ups spawn from the job's template and join the
        routing pool once warmed, downs drain newest-first (streams
        fail over with forced prefixes — never dropped). Synchronous:
        the socket timeout is sized to the drain/warm budget like
        :meth:`rolling_restart_inference_job`."""
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        sock = max(self.timeout, drain_timeout * 2 + 240.0)
        return json_request(
            "POST",
            f"{self.admin_url}/inference_jobs/{job_id}/scale",
            {"workers": int(workers), "drain_timeout": drain_timeout},
            headers=headers, timeout=sock)

    def get_inference_job_autoscaler(self, job_id: str
                                     ) -> Dict[str, Any]:
        """The job's routing pool + autoscaler state (bounds, pending
        warmups/drains, cooldown)."""
        return self._call("GET",
                          f"/inference_jobs/{job_id}/autoscaler")

    def backup(self, path: str) -> Dict[str, Any]:
        """Snapshot the admin's MetaStore to ``path`` ON THE ADMIN
        HOST (SQLite online backup — consistent under live traffic).
        Run before risky operations; see docs/operations.md "Admin
        death & recovery"."""
        return self._call("POST", "/system/backup", {"path": path})

    # ---- online prediction ----
    def predict(self, predictor_url: str, queries: Sequence[Any],
                timeout: Optional[float] = None,
                sampling: Optional[Dict[str, Any]] = None,
                trace_id: Optional[str] = None,
                slo: Optional[str] = None,
                retry_on_503: bool = True) -> List[Any]:
        """``sampling`` (generation jobs): {temperature, top_k, top_p,
        seed, eos_id, max_new, adapter_id} forwarded to the decode
        loop; omit for greedy defaults. ``max_new`` is clamped by the
        worker's configured cap. ``trace_id`` rides as
        ``X-Rafiki-Trace-Id`` so this request's timeline can be pulled
        from the predictor's and workers' ``/debug/requests``.

        ``slo`` (``interactive``/``batch``/``background``): the
        request's admission class; omit for the job's default.
        Best-effort classes admit after interactive, may be preempted
        (resuming token-exact), and may be SHED under overload.

        Three distinct structured 503s, all retried ONCE after
        honoring the server's ``retry_after_s`` (capped at
        ``MAX_RETRY_AFTER_S``): a *shed* 503
        (``HttpStatusError.shed`` — overload backpressure on a
        best-effort class; retrying after the hint is expected to
        work), a *data-plane-down* 503
        (``HttpStatusError.data_plane_down`` — the kvd is being
        respawned with WAL replay; shed-like, the honored retry is
        expected to land), and a breaker *fast-fail* 503 (fleet
        down/draining; retrying probes the outage). When the retry
        also fails the typed
        :class:`~rafiki_tpu.utils.http.HttpStatusError` surfaces with
        ``.shed``/``.data_plane_down``/``.retry_after_s`` so callers
        can schedule their own backoff. Disable with
        ``retry_on_503=False``."""
        body: Dict[str, Any] = {"queries": _jsonable(queries)}
        if timeout is not None:
            body["timeout"] = timeout
        if sampling:
            body["sampling"] = sampling
        if slo is not None:
            body["slo"] = slo
        # the socket must outlive the server-side gather deadline, or a
        # slow-but-working predictor (first-request compile) looks dead
        sock_timeout = self.timeout if timeout is None else \
            max(self.timeout, timeout + 30.0)
        url = f"{predictor_url.rstrip('/')}/predict"
        headers = _trace_headers(trace_id)
        try:
            out = json_request("POST", url, body, headers=headers,
                               timeout=sock_timeout)
        except HttpStatusError as e:
            # shed 503s and breaker fast-fail 503s both carry the
            # structured retry hint; e.shed tells them apart when the
            # retry below also fails and the error reaches the caller
            retry_after = e.retry_after_s
            if not (retry_on_503 and e.status == 503
                    and retry_after is not None):
                raise
            time.sleep(min(max(0.0, retry_after), MAX_RETRY_AFTER_S))
            out = json_request("POST", url, body, headers=headers,
                               timeout=sock_timeout)
        return out["predictions"]

    def predict_stream(self, predictor_url: str, queries: Sequence[Any],
                       timeout: Optional[float] = None,
                       sampling: Optional[Dict[str, Any]] = None,
                       trace_id: Optional[str] = None,
                       resume: Optional[Sequence[Optional[str]]] = None,
                       auto_resume: int = 1,
                       slo: Optional[str] = None):
        """Streaming generation: yields the predictor's SSE events —
        ``{"delta": {qi: text}}`` per new-token batch (append to query
        qi's output), rarely ``{"replace": {qi: text}}`` (authoritative
        text diverged from the streamed prefix — overwrite, don't
        append), then one ``{"done": True, "predictions": [...]}`` (or
        done+error). Every stream ends with a done event. Only
        meaningful against generation (decode-loop) inference jobs.

        **Resumable errors**: when the predictor loses every healthy
        worker mid-stream it ends the stream with a *resumable* event
        carrying the delivered text. Up to ``auto_resume`` times, this
        generator transparently re-requests with that partial as
        ``resume`` (after honoring ``retry_after_s``) and the stream
        continues where it stopped — no text re-delivered or lost.
        When resumes are exhausted (or ``auto_resume=0``) the terminal
        event is a typed :class:`StreamInterrupted` instead of a bare
        error string, so callers can resume on their own schedule.
        ``resume`` seeds the first request (continuing an earlier
        interrupted stream).

        ``slo``: admission class (omit for the job default). A shed /
        fast-fail 503 at stream open is retried ONCE after honoring
        ``retry_after_s``; a second refusal raises the typed
        :class:`~rafiki_tpu.utils.http.HttpStatusError` whose
        ``.shed`` distinguishes overload backpressure from a dead
        fleet."""
        from ..utils.http import STREAM_BUDGET_S, sse_request

        # a request queued behind busy decode slots can legitimately
        # produce no deltas until near the server's WHOLE-stream budget
        # — so with no explicit timeout, size the per-EVENT wait to the
        # server's stream budget (every stream ends with a terminal
        # done event within it), not the unary self.timeout. Connection
        # establishment keeps the short self.timeout: a down host must
        # fail fast, not after the stream budget.
        server_budget = STREAM_BUDGET_S if timeout is None else timeout
        partial = list(resume) if resume else None
        resumes_left = max(0, int(auto_resume))
        retry_503_left = 1
        while True:
            body: Dict[str, Any] = {"queries": _jsonable(queries)}
            if timeout is not None:
                body["timeout"] = timeout
            if sampling:
                body["sampling"] = sampling
            if slo is not None:
                body["slo"] = slo
            if partial and any(p for p in partial):
                body["resume"] = [p if isinstance(p, str) else None
                                  for p in partial]
            resumed_here = False
            try:
                for ev in sse_request(
                        "POST",
                        f"{predictor_url.rstrip('/')}/predict_stream",
                        body, headers=_trace_headers(trace_id),
                        timeout=self.timeout,
                        read_timeout=max(self.timeout,
                                         server_budget + 30.0)):
                    if not (isinstance(ev, dict) and ev.get("done")
                            and ev.get("resumable")):
                        yield ev
                        continue
                    partial = list(ev.get("partial") or [])
                    if resumes_left > 0:
                        # resume even with NO delivered text: an empty
                        # resume is just a fresh request after
                        # retry_after_s — the stream twin of predict()'s
                        # structured-503 retry
                        resumes_left -= 1
                        resumed_here = True
                        time.sleep(min(
                            max(0.0,
                                float(ev.get("retry_after_s") or 0)),
                            MAX_RETRY_AFTER_S))
                        break  # re-request with the partial as resume
                    yield StreamInterrupted(
                        error=str(ev.get("error") or ""),
                        partial=partial, qid=str(ev.get("qid") or ""),
                        trace_id=str(ev.get("trace_id") or ""),
                        retry_after_s=float(ev.get("retry_after_s")
                                            or 0),
                        raw=ev)
            except HttpStatusError as e:
                # the stream never opened: a shed 503 (overload
                # backpressure — e.shed) or a breaker fast-fail 503.
                # One honored retry, like predict(); the second
                # refusal raises the typed error for the caller.
                if not (e.status == 503 and retry_503_left > 0
                        and e.retry_after_s is not None):
                    raise
                retry_503_left -= 1
                time.sleep(min(max(0.0, e.retry_after_s),
                               MAX_RETRY_AFTER_S))
                continue
            if not resumed_here:
                return


def _trace_headers(trace_id: Optional[str]) -> Optional[Dict[str, str]]:
    return {"X-Rafiki-Trace-Id": trace_id} if trace_id else None


def _jsonable(queries: Sequence[Any]) -> List[Any]:
    import numpy as np

    out = []
    for q in queries:
        if isinstance(q, np.ndarray) or hasattr(q, "tolist"):
            out.append(np.asarray(q).tolist())
        else:
            out.append(q)
    return out
