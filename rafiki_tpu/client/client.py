"""Client SDK: the user-facing mirror of the Admin REST API.

Parity target: the reference's ``rafiki/client/client.py`` ``Client``
surface (SURVEY.md §2 "Client SDK", §1 layer 2): ``login``,
``create_model``, ``create_dataset``, ``create_train_job``,
``get_train_job``, ``get_best_trials_of_train_job``,
``create_inference_job``, and a ``predict`` helper against the deployed
predictor endpoint.
"""

from __future__ import annotations

import base64
import time
from typing import Any, Dict, List, Optional, Sequence, Type

from ..utils.http import json_request


class Client:
    def __init__(self, admin_url: str = "http://127.0.0.1:3000",
                 timeout: float = 120.0) -> None:
        self.admin_url = admin_url.rstrip("/")
        self.timeout = timeout
        self._token: Optional[str] = None

    # ---- plumbing ----
    def _call(self, method: str, path: str,
              body: Any = None) -> Any:
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        return json_request(method, f"{self.admin_url}{path}", body,
                            headers=headers, timeout=self.timeout)

    # ---- auth ----
    def login(self, email: str, password: str) -> Dict[str, Any]:
        out = json_request("POST", f"{self.admin_url}/tokens",
                           {"email": email, "password": password},
                           timeout=self.timeout)
        self._token = out["token"]
        return out

    def create_user(self, email: str, password: str,
                    user_type: str = "APP_DEVELOPER") -> Dict[str, Any]:
        return self._call("POST", "/users",
                          {"email": email, "password": password,
                           "user_type": user_type})

    # ---- models ----
    def create_model(self, name: str, task: str, model_class: Any,
                     access_right: str = "PRIVATE") -> Dict[str, Any]:
        """``model_class`` may be a BaseModel subclass (its module source
        is shipped) or raw source bytes + ``name:class`` string."""
        if isinstance(model_class, (bytes, bytearray)):
            raise TypeError("pass (bytes, class_name) via create_model_raw")
        from ..model.base import serialize_model_class

        model_bytes = serialize_model_class(model_class)
        return self.create_model_raw(name, task, model_class.__name__,
                                     model_bytes, access_right)

    def create_model_raw(self, name: str, task: str, class_name: str,
                         model_bytes: bytes,
                         access_right: str = "PRIVATE") -> Dict[str, Any]:
        return self._call("POST", "/models", {
            "name": name, "task": task, "model_class": class_name,
            "model_bytes": base64.b64encode(model_bytes).decode(),
            "access_right": access_right})

    def get_models(self, task: Optional[str] = None) -> List[Dict]:
        out = self._call("GET", "/models")
        return [m for m in out if task is None or m["task"] == task]

    # ---- datasets ----
    def create_dataset(self, name: str, task: str,
                       uri: str) -> Dict[str, Any]:
        return self._call("POST", "/datasets",
                          {"name": name, "task": task, "uri": uri})

    # ---- train jobs ----
    def create_train_job(self, app: str, task: str, train_dataset_id: str,
                         val_dataset_id: str,
                         budget: Optional[Dict[str, Any]] = None,
                         model_ids: Optional[List[str]] = None,
                         train_args: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        return self._call("POST", "/train_jobs", {
            "app": app, "task": task,
            "train_dataset_id": train_dataset_id,
            "val_dataset_id": val_dataset_id,
            "budget": budget or {"TRIAL_COUNT": 5},
            "model_ids": model_ids, "train_args": train_args})

    def get_train_job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/train_jobs/{job_id}")

    def get_train_job_of_app(self, app: str) -> Dict[str, Any]:
        return self._call("GET", f"/train_jobs/app/{app}")

    def stop_train_job(self, job_id: str) -> None:
        self._call("POST", f"/train_jobs/{job_id}/stop")

    def wait_until_train_job_finished(self, job_id: str,
                                      timeout: float = 1800.0,
                                      poll_s: float = 1.0
                                      ) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.get_train_job(job_id)
            if job["status"] in ("STOPPED", "ERRORED"):
                return job
            time.sleep(poll_s)
        raise TimeoutError(f"train job {job_id} still running")

    def get_trials_of_train_job(self, job_id: str) -> List[Dict]:
        return self._call("GET", f"/train_jobs/{job_id}/trials")

    def get_best_trials_of_train_job(self, job_id: str,
                                     max_count: int = 2) -> List[Dict]:
        return self._call("GET", f"/train_jobs/{job_id}/best_trials",
                          {"max_count": max_count})

    def get_trial_logs(self, trial_id: str) -> List[Dict]:
        return self._call("GET", f"/trials/{trial_id}/logs")

    # ---- inference jobs ----
    def create_inference_job(self, train_job_id: str,
                             max_workers: int = 2,
                             budget: Optional[Dict[str, Any]] = None
                             ) -> Dict[str, Any]:
        """``budget={"MULTI_ADAPTER": 1}`` deploys the best-N LM trials
        as ONE stacked-adapter worker (route requests with
        ``sampling={"adapter_id": i}``, i = i-th best trial) instead of
        N full replicas."""
        body: Dict[str, Any] = {"train_job_id": train_job_id,
                                "max_workers": max_workers}
        if budget:
            body["budget"] = budget
        return self._call("POST", "/inference_jobs", body)

    def get_inference_job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/inference_jobs/{job_id}")

    def get_inference_job_health(self, job_id: str) -> Dict[str, Any]:
        """The predictor's live ``/health`` (req/s, latency
        percentiles, per-worker engine/drop counters), proxied through
        the admin — the dashboard's data source, usable from scripts."""
        return self._call("GET", f"/inference_jobs/{job_id}/health")

    def stop_inference_job(self, job_id: str) -> None:
        self._call("POST", f"/inference_jobs/{job_id}/stop")

    # ---- online prediction ----
    def predict(self, predictor_url: str, queries: Sequence[Any],
                timeout: Optional[float] = None,
                sampling: Optional[Dict[str, Any]] = None,
                trace_id: Optional[str] = None) -> List[Any]:
        """``sampling`` (generation jobs): {temperature, top_k, top_p,
        seed, eos_id, max_new, adapter_id} forwarded to the decode
        loop; omit for greedy defaults. ``max_new`` is clamped by the
        worker's configured cap. ``trace_id`` rides as
        ``X-Rafiki-Trace-Id`` so this request's timeline can be pulled
        from the predictor's and workers' ``/debug/requests``."""
        body: Dict[str, Any] = {"queries": _jsonable(queries)}
        if timeout is not None:
            body["timeout"] = timeout
        if sampling:
            body["sampling"] = sampling
        # the socket must outlive the server-side gather deadline, or a
        # slow-but-working predictor (first-request compile) looks dead
        sock_timeout = self.timeout if timeout is None else \
            max(self.timeout, timeout + 30.0)
        out = json_request("POST", f"{predictor_url.rstrip('/')}/predict",
                           body, headers=_trace_headers(trace_id),
                           timeout=sock_timeout)
        return out["predictions"]

    def predict_stream(self, predictor_url: str, queries: Sequence[Any],
                       timeout: Optional[float] = None,
                       sampling: Optional[Dict[str, Any]] = None,
                       trace_id: Optional[str] = None):
        """Streaming generation: yields the predictor's SSE events —
        ``{"delta": {qi: text}}`` per new-token batch (append to query
        qi's output), rarely ``{"replace": {qi: text}}`` (authoritative
        text diverged from the streamed prefix — overwrite, don't
        append), then one ``{"done": True, "predictions": [...]}`` (or
        done+error). Every stream ends with a done event. Only
        meaningful against generation (decode-loop) inference jobs."""
        from ..utils.http import STREAM_BUDGET_S, sse_request

        body: Dict[str, Any] = {"queries": _jsonable(queries)}
        if timeout is not None:
            body["timeout"] = timeout
        if sampling:
            body["sampling"] = sampling
        # a request queued behind busy decode slots can legitimately
        # produce no deltas until near the server's WHOLE-stream budget
        # — so with no explicit timeout, size the per-EVENT wait to the
        # server's stream budget (every stream ends with a terminal
        # done event within it), not the unary self.timeout. Connection
        # establishment keeps the short self.timeout: a down host must
        # fail fast, not after the stream budget.
        server_budget = STREAM_BUDGET_S if timeout is None else timeout
        yield from sse_request(
            "POST", f"{predictor_url.rstrip('/')}/predict_stream",
            body, headers=_trace_headers(trace_id),
            timeout=self.timeout,
            read_timeout=max(self.timeout, server_budget + 30.0))


def _trace_headers(trace_id: Optional[str]) -> Optional[Dict[str, str]]:
    return {"X-Rafiki-Trace-Id": trace_id} if trace_id else None


def _jsonable(queries: Sequence[Any]) -> List[Any]:
    import numpy as np

    out = []
    for q in queries:
        if isinstance(q, np.ndarray) or hasattr(q, "tolist"):
            out.append(np.asarray(q).tolist())
        else:
            out.append(q)
    return out
