"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
axis.

The fifth parallelism axis (next to dp / tp+fsdp / sp / ep): layers are
partitioned into S stages living on S devices of a ``pipe`` mesh axis,
and microbatches stream through — device s computes microbatch m while
device s+1 computes m−1, activations hopping stage-to-stage over
neighbor ICI links. TPU-first shape:

- **Stacked stage parameters**: the caller stacks per-stage params into
  leading-dim-S pytrees and shards dim 0 over ``pipe`` — each device
  holds exactly its stage's weights (same convention as the MoE expert
  stack). ``stack_stage_params`` builds the stack from per-stage trees.
- **One ``lax.scan`` over ticks** inside a ``shard_map``: every device
  runs the SAME program (SPMD) — receive the previous stage's
  activation via ``ppermute``, stage 0 instead injects the next
  microbatch, apply the local stage, and the last stage emits into the
  output buffer. M microbatches through S stages take M+S−1 ticks; the
  S−1 bubble ticks are the classic pipeline cost (amortized by M ≫ S).
- **Differentiable for free**: ``ppermute`` has a transpose rule and the
  loop is a ``scan``, so ``jax.grad`` runs the reverse pipeline without
  a hand-written backward. Pass ``remat=True`` to rematerialize each
  stage application in the backward (activation memory then scales with
  ticks, not ticks × stage depth).

This module is the primitive; templates compose it by making
``stage_fn`` a chunk of their block stack.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..ops.common import shard_map_kernels

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage: Sequence[Any]) -> Any:
    """Stack S per-stage pytrees into one leading-dim-S pytree (the
    layout whose dim 0 shards over the ``pipe`` axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage)


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any, x_micro: jnp.ndarray, mesh,
                   axis: str = PIPE_AXIS, batch_axis: str = None,
                   remat: bool = False) -> jnp.ndarray:
    """Run ``y_m = stage_{S-1}(… stage_0(x_m))`` for every microbatch.

    ``stage_fn(params_slice, x) -> y`` is one stage (activation shapes
    preserved); ``stacked_params`` has leading dim S == the ``axis``
    size on every leaf (one stage per pipe device); ``x_micro`` is
    ``(M, batch, …)`` microbatched input. ``batch_axis`` names a second
    mesh axis to shard each microbatch's batch dim over (pipe × data).
    Returns ``(M, batch, …)`` outputs with the input's shardings.
    Differentiable end-to-end.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_stages = mesh.shape[axis]
    m_micro = x_micro.shape[0]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            # the per-device strip below keeps exactly ONE stage slice;
            # any other leading dim would silently drop stages
            raise ValueError(
                f"stacked_params leading dim {leaf.shape[0]} != "
                f"mesh[{axis!r}] size {n_stages} (one stage per pipe "
                "device)")
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def stage_spec(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    param_specs = jax.tree_util.tree_map(stage_spec, stacked_params)
    x_spec = P(None, batch_axis, *([None] * (x_micro.ndim - 2)))

    @functools.partial(
        shard_map_kernels, mesh=mesh,
        in_specs=(param_specs, x_spec), out_specs=x_spec)
    def _pipeline(params_local, x_all):
        s = jax.lax.axis_index(axis)
        # local stage weights: strip the sharded singleton stage dim
        p_stage = jax.tree_util.tree_map(lambda a: a[0], params_local)
        act0 = jnp.zeros_like(x_all[0])
        out0 = jnp.zeros_like(x_all)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            act, out = carry
            # previous stage's activation arrives over the ring; stage 0
            # injects the t-th microbatch instead (clip: bubble ticks
            # recompute a stale microbatch whose result is never used)
            inbound = jax.lax.ppermute(act, axis, perm)
            feed_idx = jnp.clip(t, 0, m_micro - 1)
            feed = jnp.where(
                s == 0,
                jax.lax.dynamic_index_in_dim(x_all, feed_idx, 0,
                                             keepdims=False),
                inbound)
            y = body(p_stage, feed)
            # the LAST stage finishes microbatch t-(S-1) at tick t
            emit = t - (n_stages - 1)
            idx = jnp.clip(emit, 0, m_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out, idx, 0,
                                               keepdims=False)
            val = jnp.where((emit >= 0) & (s == n_stages - 1), y, cur)
            out = jax.lax.dynamic_update_index_in_dim(out, val, idx, 0)
            return (y, out), None

        (_, out), _ = jax.lax.scan(tick, (act0, out0),
                                   jnp.arange(m_micro + n_stages - 1))
        # result lives on the last stage; the masked psum replicates it
        # (every other stage contributes zeros)
        return jax.lax.psum(
            jnp.where(s == n_stages - 1, out, jnp.zeros_like(out)),
            axis)

    shard = NamedSharding(mesh, x_spec)
    p_shard = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), param_specs)
    stacked_params = jax.tree_util.tree_map(jax.device_put,
                                            stacked_params, p_shard)
    return _pipeline(stacked_params, jax.device_put(x_micro, shard))


def pipeline_oracle(stage_fn, per_stage_params: Sequence[Any],
                    x_micro: jnp.ndarray) -> jnp.ndarray:
    """Sequential reference: the same math with no pipeline (tests)."""
    ys = []
    for m in range(x_micro.shape[0]):
        h = x_micro[m]
        for p in per_stage_params:
            h = stage_fn(p, h)
        ys.append(h)
    return jnp.stack(ys)
