"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
axis.

The fifth parallelism axis (next to dp / tp+fsdp / sp / ep): layers are
partitioned into S stages living on S devices of a ``pipe`` mesh axis,
and microbatches stream through — device s computes microbatch m while
device s+1 computes m−1, activations hopping stage-to-stage over
neighbor ICI links. TPU-first shape:

- **Stacked stage parameters**: the caller stacks per-stage params into
  leading-dim-S pytrees and shards dim 0 over ``pipe`` — each device
  holds exactly its stage's weights (same convention as the MoE expert
  stack). ``stack_stage_params`` builds the stack from per-stage trees.
- **One ``lax.scan`` over ticks** inside a ``shard_map``: every device
  runs the SAME program (SPMD) — receive the previous stage's
  activation via ``ppermute``, stage 0 instead injects the next
  microbatch, apply the local stage, and the last stage emits into the
  output buffer. M microbatches through S stages take M+S−1 ticks; the
  S−1 bubble ticks are the classic pipeline cost (amortized by M ≫ S).
- **Differentiable for free**: ``ppermute`` has a transpose rule and the
  loop is a ``scan``, so ``jax.grad`` runs the reverse pipeline without
  a hand-written backward. Pass ``remat=True`` to rematerialize each
  stage application in the backward (activation memory then scales with
  ticks, not ticks × stage depth).

This module is the primitive; templates compose it by making
``stage_fn`` a chunk of their block stack.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..ops.common import shard_map_kernels

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage: Sequence[Any]) -> Any:
    """Stack S per-stage pytrees into one leading-dim-S pytree (the
    layout whose dim 0 shards over the ``pipe`` axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any],
                   stacked_params: Any, x_micro: Any, mesh,
                   axis: str = PIPE_AXIS, batch_axis: str = None,
                   remat: bool = False) -> Any:
    """Run ``y_m = stage_{S-1}(… stage_0(x_m))`` for every microbatch.

    ``stage_fn(params_slice, x) -> y`` is one stage; ``stacked_params``
    has leading dim S == the ``axis`` size on every leaf (one stage per
    pipe device); ``x_micro`` is a PYTREE whose every leaf has leading
    microbatch dim M — real models thread (hidden, positions, mask, …)
    through the pipe as a tuple/dict activation. The activation
    structure must be preserved by every stage (shapes too).
    ``batch_axis`` names a second mesh axis to shard each leaf's dim 1
    (the batch dim) over (pipe × data). Returns the ``(M, …)`` output
    pytree with the input's shardings. Differentiable end-to-end.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_stages = mesh.shape[axis]
    x_leaves = jax.tree_util.tree_leaves(x_micro)
    m_micro = x_leaves[0].shape[0]
    for leaf in x_leaves:
        if leaf.shape[0] != m_micro:
            raise ValueError("all activation leaves must share the "
                             "leading microbatch dim")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            # the per-device strip below keeps exactly ONE stage slice;
            # any other leading dim would silently drop stages
            raise ValueError(
                f"stacked_params leading dim {leaf.shape[0]} != "
                f"mesh[{axis!r}] size {n_stages} (one stage per pipe "
                "device)")
    body = jax.checkpoint(stage_fn) if remat else stage_fn
    tmap = jax.tree_util.tree_map

    def stage_spec(leaf):
        return P(axis, *([None] * (leaf.ndim - 1)))

    param_specs = tmap(stage_spec, stacked_params)

    def act_spec(leaf):
        # dim 0 = microbatch (never sharded), dim 1 = batch (sharded
        # over batch_axis when present); rank-1 leaves (per-microbatch
        # scalars/masks) have no batch dim to shard
        if leaf.ndim < 2:
            return P(*([None] * leaf.ndim))
        return P(None, batch_axis, *([None] * (leaf.ndim - 2)))

    x_specs = tmap(act_spec, x_micro)

    @functools.partial(
        shard_map_kernels, mesh=mesh,
        in_specs=(param_specs, x_specs), out_specs=x_specs)
    def _pipeline(params_local, x_all):
        s = jax.lax.axis_index(axis)
        # local stage weights: strip the sharded singleton stage dim
        p_stage = tmap(lambda a: a[0], params_local)
        act0 = tmap(lambda a: jnp.zeros_like(a[0]), x_all)
        out0 = tmap(jnp.zeros_like, x_all)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            act, out = carry
            # previous stage's activation arrives over the ring; stage 0
            # injects the t-th microbatch instead (clip: bubble ticks
            # recompute a stale microbatch whose result is never used)
            inbound = tmap(lambda a: jax.lax.ppermute(a, axis, perm),
                           act)
            feed_idx = jnp.clip(t, 0, m_micro - 1)
            feed = tmap(
                lambda xs, inb: jnp.where(
                    s == 0,
                    jax.lax.dynamic_index_in_dim(xs, feed_idx, 0,
                                                 keepdims=False),
                    inb), x_all, inbound)
            y = body(p_stage, feed)
            # the LAST stage finishes microbatch t-(S-1) at tick t
            emit = t - (n_stages - 1)
            idx = jnp.clip(emit, 0, m_micro - 1)
            is_emit = (emit >= 0) & (s == n_stages - 1)

            def emit_leaf(o, yl):
                cur = jax.lax.dynamic_index_in_dim(o, idx, 0,
                                                   keepdims=False)
                val = jnp.where(is_emit, yl, cur)
                return jax.lax.dynamic_update_index_in_dim(o, val, idx,
                                                           0)

            out = tmap(emit_leaf, out, y)
            return (y, out), None

        (_, out), _ = jax.lax.scan(tick, (act0, out0),
                                   jnp.arange(m_micro + n_stages - 1))
        # result lives on the last stage; the masked psum replicates it
        # (every other stage contributes zeros)
        return tmap(
            lambda o: jax.lax.psum(
                jnp.where(s == n_stages - 1, o, jnp.zeros_like(o)),
                axis), out)

    x_shard = tmap(lambda spec: NamedSharding(mesh, spec), x_specs)
    p_shard = tmap(lambda spec: NamedSharding(mesh, spec), param_specs)
    stacked_params = tmap(jax.device_put, stacked_params, p_shard)
    x_micro = tmap(jax.device_put, x_micro, x_shard)
    # jit the shard_map: required for stage bodies that contain inner
    # calls (flax apply under lax.scan — eager shard_map cannot host
    # closed_call). NOTE for EAGER repeat-callers: this closure is
    # fresh per call, so back-to-back eager pipeline_apply calls
    # retrace — put your training step under jax.jit (the templates
    # do), which traces this whole function once
    return jax.jit(_pipeline)(stacked_params, x_micro)


def pipeline_oracle(stage_fn, per_stage_params: Sequence[Any],
                    x_micro: jnp.ndarray) -> jnp.ndarray:
    """Sequential reference: the same math with no pipeline (tests)."""
    ys = []
    for m in range(x_micro.shape[0]):
        h = x_micro[m]
        for p in per_stage_params:
            h = stage_fn(p, h)
        ys.append(h)
    return jnp.stack(ys)
