"""Sharding vocabulary: named meshes + common partition specs.

The reference has no in-trial parallelism (SURVEY.md §2.2); here each trial
can itself be data-parallel (ResNet/ViT over a sub-mesh) or 2-D
fsdp×tensor-parallel (Llama LoRA). Everything goes through
``jax.sharding.NamedSharding`` on a named mesh so XLA inserts the
collectives (psum/all-gather/reduce-scatter) — never hand-written.

Axis conventions (used across the model zoo):
- ``data``: batch axis (DP); gradients all-reduce over it.
- ``model``: tensor-parallel axis; weights split over it, activations
  all-gather/reduce-scatter around matmuls.
A 1-D mesh uses ``data`` only; the 2-D Llama mesh is ``(data, model)``
with fsdp sharding weights over ``data`` as well.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(devices: Optional[Sequence[Any]] = None,
              data: Optional[int] = None, model: int = 1):
    """Build a (data, model) mesh over ``devices`` (default: all local)."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"data*model = {data * model} != {n} devices")
    arr = np.array(devs, dtype=object).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh):
    """Shard the leading (batch) dim over ``data``, replicate elsewhere."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS))


def pad_batch_to_axis(x, mesh, axis=None):
    """Tile/slice ``x``'s leading dim up to the next multiple of a mesh
    axis size (default ``data``) so it can shard over it. One place for
    the round-up arithmetic the dryrun legs and tests need."""
    import jax.numpy as jnp

    n = mesh.shape[DATA_AXIS if axis is None else axis]
    b = x.shape[0]
    if b % n == 0:
        return x
    target = -(-b // n) * n
    reps = -(-target // b)
    return jnp.concatenate([x] * reps, axis=0)[:target]


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def shard_batch(batch: Any, mesh):
    """Place a host batch with its leading dim sharded over ``data``."""
    import jax

    s = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), batch)


def replicate_tree(tree: Any, mesh):
    import jax

    s = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


def overlap_compiler_options(enabled: bool) -> Dict[str, Any]:
    """XLA options overlapping the fsdp collectives with compute.

    With fsdp sharding, every step all-gathers each weight before its
    matmul and reduce-scatters the gradient after; by default XLA
    serializes those collectives against the surrounding compute. These
    flags turn on async collectives + the latency-hiding scheduler so
    the gather of layer k+1's weights runs under layer k's matmuls —
    the ``overlap_collectives`` knob's whole effect, applied via
    ``jax.jit(..., compiler_options=...)`` so it is per-program (a
    searchable schedule), not a process-global ``XLA_FLAGS`` setting.

    TPU backend only: the flags are TPU-specific and the CPU compiler
    rejects unknown options, so elsewhere (and when disabled) this
    returns ``{}`` — the knob is then compile-neutral, which is exactly
    what the CPU-fallback bench provenance records.
    """
    import jax

    if not enabled or jax.default_backend() != "tpu":
        return {}
    return {
        "xla_tpu_enable_async_collective_fusion": "true",
        "xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
        "xla_tpu_overlap_compute_collective_tc": "true",
        "xla_tpu_enable_all_experimental_scheduler_features": "true",
    }


# ---------------------------------------------------------------------------
# Parameter partitioning by name rules (fsdp / tensor-parallel)
# ---------------------------------------------------------------------------

def fsdp_param_spec(path: str, shape: Sequence[int], mesh,
                    min_size: int = 2 ** 16, base=None):
    """FSDP-style spec: shard a weight's largest divisible dim over
    ``data``. Small tensors stay replicated (collective overhead beats the
    memory win below ``min_size`` elements). ``base`` is an existing
    (e.g. tensor-parallel) spec to extend — already-sharded dims are
    skipped."""
    from jax.sharding import PartitionSpec as P

    taken = list(base) if base is not None else []
    taken += [None] * (len(shape) - len(taken))
    n_data = mesh.shape[DATA_AXIS]
    if math.prod(shape) >= min_size:
        # prefer the largest free dim divisible by the axis size
        for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
            if taken[i] is None and shape[i] % n_data == 0:
                taken[i] = DATA_AXIS
                break
    while taken and taken[-1] is None:  # canonical form: no trailing Nones
        taken.pop()
    return P(*taken)


def tp_param_spec(path: str, shape: Sequence[int], mesh,
                  rules: Dict[str, int]):
    """Tensor-parallel spec from substring rules: ``rules`` maps a
    parameter-path substring to the dim index sharded over ``model``
    (negative dims allowed). First matching rule wins."""
    from jax.sharding import PartitionSpec as P

    if not shape:
        return P()
    n_model = mesh.shape[MODEL_AXIS]
    for frag, dim in rules.items():
        if frag in path:
            d = dim % len(shape)
            if shape[d] % n_model == 0:
                spec: list = [None] * len(shape)
                spec[d] = MODEL_AXIS
                return P(*spec)
    return P()


def combine_specs(a, b):
    """Merge two PartitionSpecs dim-wise (error on conflicts)."""
    from jax.sharding import PartitionSpec as P

    la, lb = list(a), list(b)
    n = max(len(la), len(lb))
    la += [None] * (n - len(la))
    lb += [None] * (n - len(lb))
    out = []
    for x, y in zip(la, lb):
        if x is not None and y is not None and x != y:
            raise ValueError(f"conflicting specs {a} vs {b}")
        out.append(x if x is not None else y)
    return P(*out)


def param_shardings(params: Any, mesh, tp_rules: Optional[Dict[str, int]]
                    = None, fsdp: bool = False, min_size: int = 2 ** 16):
    """NamedShardings for a parameter pytree by path rules.

    ``tp_rules`` shards matching weights over ``model``; ``fsdp=True``
    additionally shards (non-conflicting) large weights over ``data``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def path_str(kp) -> str:
        return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp)

    specs = {}
    for kp, leaf in flat:
        p = path_str(kp)
        shape = getattr(leaf, "shape", ())
        spec = P()
        if tp_rules:
            spec = tp_param_spec(p, shape, mesh, tp_rules)
        if fsdp and shape:
            spec = fsdp_param_spec(p, shape, mesh, min_size, base=spec)
        specs[p] = spec

    def to_sharding(kp, leaf):
        return NamedSharding(mesh, specs[path_str(kp)])

    return jax.tree_util.tree_map_with_path(to_sharding, params)
