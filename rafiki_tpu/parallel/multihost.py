"""Multi-host distributed backend: coordinator bootstrap + global meshes.

The reference's cross-machine story is NCCL/MPI inside its training
processes plus HTTP/Redis between services (SURVEY.md §5.8). The
TPU-native equivalent has two halves, and this module is the first:

- **In-program collectives across hosts**: one JAX program spanning every
  host's chips. Processes rendezvous at a coordinator
  (:func:`initialize_from_env`), after which ``jax.devices()`` is GLOBAL
  and a :func:`global_mesh` spans hosts — XLA then routes collectives
  over ICI within a slice and DCN between slices. No hand-written
  transport; the "comm backend" is the XLA runtime, which is the point.
- The host-side control plane (admin/advisor/param store) stays
  single-coordinator HTTP + kv, exactly like the reference's.

Mesh layout: DCN-connected dimensions MUST be outermost so that the
fast-changing mesh axes map to ICI neighbors
(``mesh_utils.create_hybrid_device_mesh`` encodes this); put ``data``
(gradient all-reduce, latency-tolerant, once per step) across DCN and
``model``/tensor axes inside a slice.

Verified on one box by ``tests/test_multihost.py``: two real OS
processes, each owning 4 virtual CPU devices, rendezvous at a local
coordinator and run one SPMD program over the joint 8-device mesh with a
cross-process gradient all-reduce.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

#: env contract for service processes (mirrors the knob style of
#: utils.platform): unset → single-process mode, no rendezvous.
COORD_ENV = "RAFIKI_COORDINATOR"          # "host:port"
NUM_PROCS_ENV = "RAFIKI_NUM_PROCESSES"
PROC_ID_ENV = "RAFIKI_PROCESS_ID"


def initialize_from_env(timeout_s: float = 60.0) -> bool:
    """Rendezvous this process with its peers if the env asks for it.

    Must run before any jax backend initializes. Returns True when a
    multi-process runtime was set up (``jax.devices()`` is now global),
    False for ordinary single-process mode. Idempotent.
    """
    coord = os.environ.get(COORD_ENV, "")
    if not coord:
        return False
    n_procs = os.environ.get(NUM_PROCS_ENV, "")
    proc_id = os.environ.get(PROC_ID_ENV, "")
    if not n_procs or not proc_id:
        raise ValueError(
            f"{COORD_ENV} is set but {NUM_PROCS_ENV}={n_procs!r} / "
            f"{PROC_ID_ENV}={proc_id!r}: a multi-host rendezvous needs "
            "all three (unset the coordinator for single-host mode)")
    import jax

    if getattr(initialize_from_env, "_done", False):
        return True
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(n_procs),
        process_id=int(proc_id),
        initialization_timeout=int(timeout_s))
    initialize_from_env._done = True
    return True


def global_mesh(data: Optional[int] = None, model: int = 1,
                devices: Optional[Sequence[Any]] = None):
    """A (data, model) mesh over ALL processes' devices.

    ``data`` spans hosts (outermost ⇒ DCN), ``model`` stays within a
    host's slice (innermost ⇒ ICI) — the layout that keeps tensor-
    parallel collectives off DCN. Single-process callers get the same
    mesh :func:`rafiki_tpu.parallel.sharding.make_mesh` would build.
    """
    import collections

    import jax

    from rafiki_tpu.parallel.sharding import make_mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    # order devices host-major so reshaping puts `data` across processes
    # and `model` within one process's chips
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    per_proc = collections.Counter(d.process_index for d in devs)
    if len(per_proc) > 1 and any(c % model for c in per_proc.values()):
        # a model group crossing hosts would route tensor-parallel
        # collectives over DCN — refuse rather than silently degrade
        raise ValueError(
            f"model={model} does not divide every host's local device "
            f"count {dict(per_proc)}; tensor parallelism must stay on "
            "one host's ICI")
    return make_mesh(devs, data=data, model=model)


def global_batch(local_batch: Any, mesh) -> Any:
    """Assemble each host's local batch shard into one global array tree.

    Every process passes its OWN slice of the global batch (equal sizes);
    the result is a pytree of jax global arrays sharded batch-over-
    ``data`` that any pjit step function consumes directly — the
    data-loading pattern for multi-host training (each host reads only
    its shard; no host ever materializes the global batch).
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rafiki_tpu.parallel.sharding import DATA_AXIS

    sharding = NamedSharding(mesh, P(DATA_AXIS))

    def place(x):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(x))

    return jax.tree_util.tree_map(place, local_batch)


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0
