"""Device meshes, sub-mesh allocation, and sharding vocabulary."""

from .mesh import (SubMesh, SubMeshAllocator, partition_devices,
                   submesh_env_vars)
from .pipeline import (PIPE_AXIS, pipeline_apply, pipeline_oracle,
                       stack_stage_params)
from .sharding import (DATA_AXIS, MODEL_AXIS, batch_sharding, make_mesh,
                       param_shardings, replicate_tree, replicated,
                       shard_batch)

__all__ = [
    "SubMesh", "SubMeshAllocator", "partition_devices", "submesh_env_vars",
    "PIPE_AXIS", "pipeline_apply", "pipeline_oracle", "stack_stage_params",
    "DATA_AXIS", "MODEL_AXIS", "batch_sharding", "make_mesh",
    "param_shardings", "replicate_tree", "replicated", "shard_batch",
]
