"""Device meshes and ICI-topology-aware sub-mesh partitioning.

This layer replaces the reference's "one Docker container = one GPU"
scheduling substrate (SURVEY.md §2.2, §7 "Device multi-tenancy"): a TPU
slice's chips are partitioned into *contiguous rectangular sub-meshes*, and
each concurrent trial (or inference replica) owns one sub-mesh. Contiguity
matters because intra-trial collectives (data-parallel all-reduce etc.)
must ride ICI links between physically adjacent chips; a fragmented
allocation would route gradients across the whole slice.

Partitioning strategy: read each device's ``coords`` (TPU gives (x, y, z));
arrange the slice as a grid; tile the grid into equal rectangles by
repeatedly halving the longer axis (power-of-two slot sizes — v5e slices
are powers of two). Devices without coords (CPU backend in tests) fall
back to index order, which is the degenerate 1-D grid.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


Device = Any  # jax Device


@dataclass(frozen=True)
class DeviceSpec:
    """Topology-only stand-in for a jax Device (what the device probe
    reports): enough for partitioning without holding the runtime."""

    id: int
    coords: Optional[Tuple[int, ...]] = None
    core_on_chip: int = 0
    platform: str = "cpu"

    @staticmethod
    def from_probe(d: Dict[str, Any]) -> "DeviceSpec":
        coords = d.get("coords")
        return DeviceSpec(id=int(d["id"]),
                          coords=tuple(coords) if coords else None,
                          core_on_chip=int(d.get("core_on_chip", 0)),
                          platform=d.get("platform", "cpu"))


def device_sort_key(d: Device) -> Tuple:
    coords = getattr(d, "coords", None)
    if coords is not None:
        return (0, tuple(coords), getattr(d, "core_on_chip", 0))
    return (1, d.id)


def _grid_shape(devices: Sequence[Device]) -> Tuple[int, int]:
    """Infer the (rows, cols) physical grid of a single-host slice."""
    coords = [getattr(d, "coords", None) for d in devices]
    if all(c is not None for c in coords) and len(set(coords)) == len(coords):
        xs = sorted({c[0] for c in coords})
        ys = sorted({c[1] for c in coords})
        if len(xs) * len(ys) == len(devices):
            return len(ys), len(xs)
    # fallback: near-square factorization of N in index order
    n = len(devices)
    rows = 2 ** (int(math.log2(n)) // 2) if n & (n - 1) == 0 else 1
    return rows, n // rows


def partition_devices(devices: Sequence[Device],
                      slot_size: int) -> List[List[Device]]:
    """Split ``devices`` into contiguous sub-meshes of ``slot_size``.

    Returns slots in grid order. Requires ``slot_size`` to divide the
    device count; power-of-two sizes yield rectangular ICI-contiguous
    tiles.
    """
    n = len(devices)
    if slot_size <= 0 or n % slot_size != 0:
        raise ValueError(f"slot_size {slot_size} must divide {n} devices")
    ordered = sorted(devices, key=device_sort_key)
    rows, cols = _grid_shape(ordered)
    grid = np.full((rows, cols), None, dtype=object)
    coords = [getattr(d, "coords", None) for d in ordered]
    xs = sorted({c[0] for c in coords if c is not None})
    ys = sorted({c[1] for c in coords if c is not None})
    if (all(c is not None for c in coords)
            and len({(c[0], c[1]) for c in coords}) == len(ordered)
            and (len(ys), len(xs)) == (rows, cols)):
        # coords form a full rectangle: place by physical position grid[y][x]
        x_index = {x: i for i, x in enumerate(xs)}
        y_index = {y: i for i, y in enumerate(ys)}
        for d, c in zip(ordered, coords):
            grid[y_index[c[1]], x_index[c[0]]] = d
        if any(grid[r, c] is None for r in range(rows) for c in range(cols)):
            grid = np.array(ordered, dtype=object).reshape(rows, cols)
    else:
        for idx, d in enumerate(ordered):
            grid[idx // cols, idx % cols] = d
    tile_r, tile_c = _tile_shape(rows, cols, slot_size)
    slots: List[List[Device]] = []
    for r0 in range(0, rows, tile_r):
        for c0 in range(0, cols, tile_c):
            tile = grid[r0:r0 + tile_r, c0:c0 + tile_c].reshape(-1)
            slots.append(list(tile))
    return slots


def _tile_shape(rows: int, cols: int, size: int) -> Tuple[int, int]:
    """Rectangular tile of ``size`` devices that evenly tiles rows×cols,
    built by halving the longer axis of the full grid until it fits."""
    r, c = rows, cols
    while r * c > size:
        if r >= c and r % 2 == 0 and (r // 2) * c >= size:
            r //= 2
        elif c % 2 == 0 and r * (c // 2) >= size:
            c //= 2
        elif r % 2 == 0 and (r // 2) * c >= size:
            r //= 2
        else:
            break
    if r * c != size:  # non-power-of-two fallback: strip tiling
        if cols % size == 0:
            return 1, size
        if rows % size == 0:
            return size, 1
        raise ValueError(
            f"cannot tile {rows}x{cols} grid into blocks of {size}")
    return r, c


@dataclass
class SubMesh:
    """A trial-owned contiguous device subset."""

    index: int
    devices: List[Device]

    @property
    def size(self) -> int:
        return len(self.devices)

    def mesh(self, axes: Optional[Dict[str, int]] = None):
        """Materialize a jax.sharding.Mesh over this sub-mesh.

        ``axes`` maps axis names to sizes, e.g. ``{"data": 2, "model": 2}``;
        default is a 1-D ``data`` mesh.
        """
        import jax
        from jax.sharding import Mesh

        axes = axes or {"data": self.size}
        sizes = list(axes.values())
        if math.prod(sizes) != self.size:
            raise ValueError(f"axes {axes} do not cover {self.size} devices")
        arr = np.array(self.devices, dtype=object).reshape(sizes)
        return Mesh(arr, tuple(axes.keys()))


class SubMeshAllocator:
    """Thread-safe allocator of sub-meshes to trials.

    The ServicesManager holds one of these per slice; train workers acquire
    a slot for each trial process and release it on completion — the moral
    equivalent of the reference's "give this container one GPU"
    (SURVEY.md §2 "Container manager").
    """

    def __init__(self, devices: Sequence[Device], slot_size: int) -> None:
        self._slots = [SubMesh(i, devs) for i, devs in
                       enumerate(partition_devices(devices, slot_size))]
        self._free = list(range(len(self._slots)))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    @property
    def n_slots(self) -> int:
        return len(self._slots)

    def acquire(self, timeout: Optional[float] = None) -> Optional[SubMesh]:
        with self._cv:
            if not self._cv.wait_for(lambda: bool(self._free),
                                     timeout=timeout):
                return None
            return self._slots[self._free.pop(0)]

    def release(self, submesh: SubMesh) -> None:
        with self._cv:
            if submesh.index in self._free:
                raise ValueError(f"slot {submesh.index} already free")
            self._free.append(submesh.index)
            self._free.sort()
            self._cv.notify()

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


def submesh_env_vars(platform: str, slot: SubMesh) -> Dict[str, str]:
    """Env vars that confine a *child process* to ``slot``'s devices.

    This is how one host runs N concurrent single-trial JAX processes on
    disjoint chip subsets (the Docker-GPU-mapping replacement):

    - TPU: ``TPU_VISIBLE_CHIPS`` (per-chip selection on a TPU-VM) plus
      flags that keep each process in its own local topology.
    - CPU (tests): a host-device count equal to the slot size — every
      process sees ``slot.size`` virtual devices, which exercises the same
      mesh code paths.
    """
    if platform == "tpu":
        chips = sorted({getattr(d, "id", i)
                        for i, d in enumerate(slot.devices)})
        coords = [getattr(d, "coords", None) for d in slot.devices]
        if all(c is not None for c in coords):
            # bounds follow the slot's physical tile shape (x, y, z)
            w = max(c[0] for c in coords) - min(c[0] for c in coords) + 1
            h = max(c[1] for c in coords) - min(c[1] for c in coords) + 1
            bounds = f"{w},{h},1"
        else:
            bounds = f"1,1,{len(chips)}"
        return {
            "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chips),
            "TPU_CHIPS_PER_PROCESS_BOUNDS": bounds,
            "TPU_PROCESS_BOUNDS": "1,1,1",
            "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
        }
    if platform == "cpu":
        # tests — RAFIKI_JAX_PLATFORM makes the child override via
        # jax.config too (env alone loses to an image-level sitecustomize)
        return {
            "JAX_PLATFORMS": "cpu",
            "RAFIKI_JAX_PLATFORM": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={slot.size}",
        }
    # unknown accelerator platform (e.g. a tunneled PJRT plugin): inherit
    # the parent environment — the allocator still guarantees one worker
    # per slot, but NOTHING confines the child to its slot's chips, so
    # concurrent trials would share every device. Say so loudly.
    import logging

    logging.getLogger(__name__).warning(
        "no device-confinement env vars for platform %r: child processes "
        "inherit ALL visible devices; run one trial at a time or use a "
        "tpu/cpu platform for slot isolation", platform)
    return {}
