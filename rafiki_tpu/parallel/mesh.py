"""Device meshes and ICI-topology-aware sub-mesh partitioning.

This layer replaces the reference's "one Docker container = one GPU"
scheduling substrate (SURVEY.md §2.2, §7 "Device multi-tenancy"): a TPU
slice's chips are partitioned into *contiguous rectangular sub-meshes*, and
each concurrent trial (or inference replica) owns one sub-mesh. Contiguity
matters because intra-trial collectives (data-parallel all-reduce etc.)
must ride ICI links between physically adjacent chips; a fragmented
allocation would route gradients across the whole slice.

Partitioning strategy: read each device's ``coords`` (TPU gives (x, y, z));
arrange the slice as an N-D grid; tile the grid into equal boxes by
repeatedly halving the longest even axis (power-of-two slot sizes — TPU
slices are powers of two). The grid is fully N-dimensional: a v5e 2-D
torus tiles into rectangles, a v4/v5p 3-D torus into rectangular boxes —
``coords[2]`` is honored, not flattened (VERDICT r3 weak #6: silently
falling back to index order on a 3-D torus would quietly void the
ICI-contiguity guarantee exactly on the biggest machines). Devices
without coords (CPU backend in tests) fall back to index order, the
degenerate 1-D grid.
"""

from __future__ import annotations

import itertools
import math
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


Device = Any  # jax Device


@dataclass(frozen=True)
class DeviceSpec:
    """Topology-only stand-in for a jax Device (what the device probe
    reports): enough for partitioning without holding the runtime."""

    id: int
    coords: Optional[Tuple[int, ...]] = None
    core_on_chip: int = 0
    platform: str = "cpu"

    @staticmethod
    def from_probe(d: Dict[str, Any]) -> "DeviceSpec":
        coords = d.get("coords")
        return DeviceSpec(id=int(d["id"]),
                          coords=tuple(coords) if coords else None,
                          core_on_chip=int(d.get("core_on_chip", 0)),
                          platform=d.get("platform", "cpu"))


def device_sort_key(d: Device) -> Tuple:
    coords = getattr(d, "coords", None)
    if coords is not None:
        return (0, tuple(coords), getattr(d, "core_on_chip", 0))
    return (1, d.id)


def _coord_axes(devices: Sequence[Device]) -> Optional[List[List[int]]]:
    """Per-dimension sorted coordinate values IF the devices form a full
    N-D box (unique coords, every combination present) — the condition
    under which physical placement is meaningful. None otherwise."""
    coords = [getattr(d, "coords", None) for d in devices]
    if not coords or any(c is None for c in coords):
        return None
    ndim = len(coords[0])
    if any(len(c) != ndim for c in coords):
        return None
    if len(set(coords)) != len(coords):
        return None
    axes = [sorted({c[i] for c in coords}) for i in range(ndim)]
    if math.prod(len(a) for a in axes) != len(coords):
        return None
    if set(coords) != set(itertools.product(*axes)):
        return None  # holes: not a full box
    return axes


def _grid_shape(devices: Sequence[Device]) -> Tuple[int, ...]:
    """Infer the physical N-D grid shape of a single-host slice, in
    coords order (x, y, z on TPU). Degenerate trailing dims (size 1)
    are kept — they cost nothing and preserve the bounds math."""
    axes = _coord_axes(devices)
    if axes is not None:
        return tuple(len(a) for a in axes)
    # fallback: near-square 2-D factorization of N in index order
    n = len(devices)
    rows = 2 ** (int(math.log2(n)) // 2) if n & (n - 1) == 0 else 1
    return rows, n // rows


def partition_devices(devices: Sequence[Device],
                      slot_size: int) -> List[List[Device]]:
    """Split ``devices`` into contiguous sub-meshes of ``slot_size``.

    Returns slots in grid order. Requires ``slot_size`` to divide the
    device count; power-of-two sizes yield box-shaped ICI-contiguous
    tiles on 2-D (v5e) AND 3-D (v4/v5p) topologies.
    """
    n = len(devices)
    if slot_size <= 0 or n % slot_size != 0:
        raise ValueError(f"slot_size {slot_size} must divide {n} devices")
    ordered = sorted(devices, key=device_sort_key)
    axes = _coord_axes(ordered)
    if axes is not None:
        shape = tuple(len(a) for a in axes)
        grid = np.empty(shape, dtype=object)
        index = [{v: i for i, v in enumerate(a)} for a in axes]
        for d in ordered:
            pos = tuple(ix[c] for ix, c in zip(index, d.coords))
            grid[pos] = d
    else:
        shape = _grid_shape(ordered)
        grid = np.array(ordered, dtype=object).reshape(shape)
    tile = _tile_shape_nd(shape, slot_size)
    slots: List[List[Device]] = []
    for origin in itertools.product(*(range(0, dim, t)
                                      for dim, t in zip(shape, tile))):
        sel = tuple(slice(o, o + t) for o, t in zip(origin, tile))
        slots.append(list(grid[sel].reshape(-1)))
    return slots


def _tile_shape_nd(shape: Sequence[int], size: int) -> Tuple[int, ...]:
    """Box of ``size`` devices that evenly tiles the N-D grid, built by
    halving the longest even axis until it fits (keeps tiles as close
    to cubes as the topology allows — shortest intra-slot ICI paths)."""
    dims = list(shape)
    while math.prod(dims) > size:
        for i in sorted(range(len(dims)), key=lambda i: -dims[i]):
            if dims[i] % 2 == 0 and math.prod(dims) // 2 >= size:
                dims[i] //= 2
                break
        else:
            break
    if math.prod(dims) != size:  # non-power-of-two fallback: strip tile
        for i, dim in enumerate(shape):
            if dim % size == 0:
                out = [1] * len(shape)
                out[i] = size
                return tuple(out)
        raise ValueError(
            f"cannot tile {'x'.join(map(str, shape))} grid into "
            f"blocks of {size}")
    return tuple(dims)


def _tile_shape(rows: int, cols: int, size: int) -> Tuple[int, int]:
    """2-D convenience wrapper over :func:`_tile_shape_nd`."""
    return _tile_shape_nd((rows, cols), size)  # type: ignore[return-value]


@dataclass
class SubMesh:
    """A trial-owned contiguous device subset."""

    index: int
    devices: List[Device]

    @property
    def size(self) -> int:
        return len(self.devices)

    def mesh(self, axes: Optional[Dict[str, int]] = None):
        """Materialize a jax.sharding.Mesh over this sub-mesh.

        ``axes`` maps axis names to sizes, e.g. ``{"data": 2, "model": 2}``;
        default is a 1-D ``data`` mesh.
        """
        import jax
        from jax.sharding import Mesh

        axes = axes or {"data": self.size}
        sizes = list(axes.values())
        if math.prod(sizes) != self.size:
            raise ValueError(f"axes {axes} do not cover {self.size} devices")
        arr = np.array(self.devices, dtype=object).reshape(sizes)
        return Mesh(arr, tuple(axes.keys()))


class SubMeshAllocator:
    """Thread-safe allocator of sub-meshes to trials.

    The ServicesManager holds one of these per slice; train workers acquire
    a slot for each trial process and release it on completion — the moral
    equivalent of the reference's "give this container one GPU"
    (SURVEY.md §2 "Container manager").
    """

    def __init__(self, devices: Sequence[Device], slot_size: int) -> None:
        self._slots = [SubMesh(i, devs) for i, devs in
                       enumerate(partition_devices(devices, slot_size))]
        self._free = list(range(len(self._slots)))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    @property
    def n_slots(self) -> int:
        return len(self._slots)

    def acquire(self, timeout: Optional[float] = None) -> Optional[SubMesh]:
        with self._cv:
            if not self._cv.wait_for(lambda: bool(self._free),
                                     timeout=timeout):
                return None
            return self._slots[self._free.pop(0)]

    def release(self, submesh: SubMesh) -> None:
        with self._cv:
            if submesh.index in self._free:
                raise ValueError(f"slot {submesh.index} already free")
            self._free.append(submesh.index)
            self._free.sort()
            self._cv.notify()

    def reserve(self, device_ids: Sequence[int]) -> Optional[SubMesh]:
        """Acquire the SPECIFIC slot covering exactly ``device_ids``
        (order-insensitive), or None when no free slot matches. The
        admin's boot reconciler uses this to re-reserve the sub-mesh a
        re-adopted service still physically holds — an arbitrary
        ``acquire()`` could hand the adopted worker's chips to a new
        spawn while the old process is still driving them."""
        want = sorted(int(i) for i in device_ids)
        with self._cv:
            for idx in list(self._free):
                slot = self._slots[idx]
                have = sorted(getattr(d, "id", i)
                              for i, d in enumerate(slot.devices))
                if have == want:
                    self._free.remove(idx)
                    return slot
            return None

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


def submesh_env_vars(platform: str, slot: SubMesh) -> Dict[str, str]:
    """Env vars that confine a *child process* to ``slot``'s devices.

    This is how one host runs N concurrent single-trial JAX processes on
    disjoint chip subsets (the Docker-GPU-mapping replacement):

    - TPU: ``TPU_VISIBLE_CHIPS`` (per-chip selection on a TPU-VM) plus
      flags that keep each process in its own local topology.
    - CPU (tests): a host-device count equal to the slot size — every
      process sees ``slot.size`` virtual devices, which exercises the same
      mesh code paths.
    """
    if platform == "tpu":
        chips = sorted({getattr(d, "id", i)
                        for i, d in enumerate(slot.devices)})
        coords = [getattr(d, "coords", None) for d in slot.devices]
        if all(c is not None for c in coords):
            # bounds follow the slot's physical tile extents in (x, y, z)
            # — including the z axis on 3-D tori (v4/v5p), where a 2-D
            # "w,h,1" would misdescribe any slot spanning z
            extent = [1, 1, 1]
            for dim in range(min(3, len(coords[0]))):
                vals = [c[dim] for c in coords]
                extent[dim] = max(vals) - min(vals) + 1
            bounds = ",".join(str(e) for e in extent)
        else:
            bounds = f"1,1,{len(chips)}"
        return {
            "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chips),
            "TPU_CHIPS_PER_PROCESS_BOUNDS": bounds,
            "TPU_PROCESS_BOUNDS": "1,1,1",
            "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
        }
    if platform == "cpu":
        # tests — RAFIKI_JAX_PLATFORM makes the child override via
        # jax.config too (env alone loses to an image-level sitecustomize)
        return {
            "JAX_PLATFORMS": "cpu",
            "RAFIKI_JAX_PLATFORM": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={slot.size}",
        }
    # unknown accelerator platform (e.g. a tunneled PJRT plugin): inherit
    # the parent environment — the allocator still guarantees one worker
    # per slot, but NOTHING confines the child to its slot's chips, so
    # concurrent trials would share every device. Say so loudly.
    import logging

    logging.getLogger(__name__).warning(
        "no device-confinement env vars for platform %r: child processes "
        "inherit ALL visible devices; run one trial at a time or use a "
        "tpu/cpu platform for slot isolation", platform)
    return {}
