"""Shared admission-control plumbing for the train and inference
workers: resolve the per-device memory limit a budget estimate is
checked against. The estimators themselves live with the templates
(e.g. ``models/llama_lora.py``'s ``estimate_train_device_bytes`` /
``estimate_serving_device_bytes``); the workers own the refusal
semantics."""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Sequence

log = logging.getLogger(__name__)


def resolve_device_limit(devices: Optional[Sequence[Any]] = None
                         ) -> Optional[int]:
    """Bytes of device memory one trial/deployment may plan against.

    Order: the ``RAFIKI_DEVICE_HBM_BYTES`` env override (a malformed
    value warns and falls through — a config typo must not fail every
    trial closed), then the accelerator's own
    ``memory_stats()["bytes_limit"]`` on non-CPU platforms. ``None``
    means "no limit known" (CPU hosts have elastic memory) and callers
    skip their check."""
    env = os.environ.get("RAFIKI_DEVICE_HBM_BYTES")
    if env:
        try:
            return int(float(env))
        except ValueError:
            log.warning(
                "RAFIKI_DEVICE_HBM_BYTES=%r is not a number; ignoring "
                "it for admission control", env)
    if devices is None:
        import jax

        devices = jax.local_devices()
    if devices and getattr(devices[0], "platform", "cpu") != "cpu":
        try:
            return (devices[0].memory_stats() or {}).get("bytes_limit")
        except Exception:  # rafiki: noqa[silent-except] — stats
            return None    # are optional on this backend
    return None
