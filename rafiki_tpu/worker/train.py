"""Train worker: the per-sub-mesh trial loop.

Parity target: the reference's ``worker/train.py`` (SURVEY.md §3.1): loop
until the advisor's budget is exhausted — get a proposal, build the model
template with the proposed knobs, train, evaluate, report the score, save
parameters. One worker per TPU sub-mesh replaces one container per GPU.

TPU-first deltas:
- The worker passes its sub-mesh devices into ``TrainContext`` so templates
  pjit over exactly the chips they own (device multi-tenancy, SURVEY.md §7).
- BOHB rung semantics ride the same loop: ``budget_scale`` scales epochs,
  ``warm_start_trial_id`` resumes a promoted trial from its own lower-rung
  checkpoint in the ParamStore.
- ``should_continue`` gives the advisor a per-epoch early-stop hook
  (preemption-friendly: the last completed epoch is always checkpointable).
"""

from __future__ import annotations

import traceback
from typing import Any, List, Optional, Type

from ..model.base import BaseModel, TrainContext
from ..model.log import ModelLogger
from ..store.param_store import ParamStore


class TrainWorker:
    """Runs trials against an advisor (in-proc object or HTTP client —
    both expose propose/feedback/trial_errored)."""

    def __init__(self, model_class: Type[BaseModel], advisor: Any,
                 train_dataset_path: str, val_dataset_path: str,
                 param_store: Optional[ParamStore] = None,
                 meta_store: Optional[Any] = None,
                 sub_train_job_id: str = "", model_id: str = "",
                 devices: Optional[List[Any]] = None,
                 worker_id: str = "worker-0",
                 profile_dir: Optional[str] = None,
                 knob_overrides: Optional[dict] = None) -> None:
        self.model_class = model_class
        self.advisor = advisor
        self.train_dataset_path = train_dataset_path
        self.val_dataset_path = val_dataset_path
        self.param_store = param_store or ParamStore()
        self.meta_store = meta_store
        self.sub_train_job_id = sub_train_job_id
        self.model_id = model_id
        self.devices = devices
        self.worker_id = worker_id
        self.profile_dir = profile_dir
        #: job-level knob pins (train_args["knob_overrides"]) merged over
        #: every proposal — how a job fixes e.g. max_len or batch_size
        #: regardless of what the advisor samples
        self.knob_overrides = dict(knob_overrides or {})
        self.trials_run = 0

    # ---- one trial ----
    def run_trial(self, proposal) -> Optional[float]:
        from ..advisor.base import TrialResult

        from ..model.knob import shape_signature

        if self.knob_overrides:
            proposal.knobs = {**proposal.knobs, **self.knob_overrides}
        if self.meta_store is not None:
            trial_id = self.meta_store.create_trial(
                self.sub_train_job_id, proposal.trial_no,
                model_id=self.model_id, knobs=proposal.knobs,
                worker_id=self.worker_id,
                budget_scale=proposal.budget_scale,
                shape_sig=shape_signature(
                    self.model_class.get_knob_config(), proposal.knobs))["id"]
        else:
            trial_id = f"{self.worker_id}-t{proposal.trial_no}"

        logger = ModelLogger()
        if self.meta_store is not None:
            logger.sink = lambda rec: self.meta_store.add_trial_log(
                trial_id, rec.kind, rec.data, rec.time)

        try:
            self.model_class.validate_knobs(proposal.knobs)
            model = self.model_class(**proposal.knobs)
            shared = None
            if proposal.warm_start_trial_id:
                shared = self.param_store.load(proposal.warm_start_trial_id)
            trial_profile_dir = None
            if self.profile_dir:
                import os

                trial_profile_dir = os.path.join(self.profile_dir, trial_id)
                os.makedirs(trial_profile_dir, exist_ok=True)
            ctx = TrainContext(devices=self.devices,
                               budget_scale=proposal.budget_scale,
                               shared_params=shared, logger=logger,
                               trial_id=trial_id,
                               profile_dir=trial_profile_dir)
            if trial_profile_dir:
                # per-trial jax.profiler trace (SURVEY.md §5.1): XLA/HLO
                # timing + (on TPU) hardware counters, viewable in
                # TensorBoard / Perfetto
                import jax

                with jax.profiler.trace(trial_profile_dir):
                    model.train(self.train_dataset_path, ctx)
            else:
                model.train(self.train_dataset_path, ctx)
            score = float(model.evaluate(self.val_dataset_path))

            self.param_store.save(trial_id, model.dump_parameters())
            model.destroy()
            if self.meta_store is not None:
                self.meta_store.mark_trial_completed(trial_id, score,
                                                     params_saved=True)
            self.advisor.feedback(TrialResult(
                trial_no=proposal.trial_no, knobs=proposal.knobs,
                score=score, trial_id=trial_id,
                budget_scale=proposal.budget_scale, meta=proposal.meta))
            self.trials_run += 1
            return score
        except Exception as e:  # trial-level fault isolation (SURVEY.md §5.3)
            if self.meta_store is not None:
                self.meta_store.mark_trial_errored(
                    trial_id, f"{e}\n{traceback.format_exc()}")
            self.advisor.trial_errored(proposal.trial_no)
            return None

    # ---- the loop ----
    def run(self, max_trials: Optional[int] = None) -> int:
        """Pull proposals until the advisor says stop; returns #trials."""
        n = 0
        while max_trials is None or n < max_trials:
            proposal = self.advisor.propose()
            if not proposal.is_valid:
                break
            self.run_trial(proposal)
            n += 1
        return n


def main(argv: Optional[list] = None) -> int:
    """Service entrypoint: ``python -m rafiki_tpu.worker.train``.

    Spawned by the ServicesManager with a JSON config file; connects to the
    advisor service over HTTP and to the shared stores.
    """
    import argparse
    import json

    from ..parallel.multihost import initialize_from_env
    from ..utils.platform import apply_platform_env

    apply_platform_env()  # before any jax backend initializes
    initialize_from_env()  # multi-host rendezvous (no-op if unconfigured)

    from ..advisor.service import AdvisorClient
    from ..model.base import load_model_class
    from ..store.meta_store import MetaStore

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True,
                        help="JSON: {advisor_url, model_file, model_class, "
                             "train_dataset, val_dataset, param_store_uri, "
                             "meta_store_path, sub_train_job_id, worker_id}")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)

    with open(cfg["model_file"], "rb") as f:
        model_class = load_model_class(f.read(), cfg["model_class"])
    meta_store = (MetaStore(cfg["meta_store_path"])
                  if cfg.get("meta_store_path") else None)
    worker = TrainWorker(
        model_class=model_class,
        advisor=AdvisorClient(cfg["advisor_url"]),
        train_dataset_path=cfg["train_dataset"],
        val_dataset_path=cfg["val_dataset"],
        param_store=ParamStore.from_uri(cfg.get("param_store_uri", "mem://")),
        meta_store=meta_store,
        sub_train_job_id=cfg.get("sub_train_job_id", ""),
        model_id=cfg.get("model_id", ""),
        worker_id=cfg.get("worker_id", "worker-0"),
        profile_dir=cfg.get("profile_dir"),
        knob_overrides=cfg.get("knob_overrides"))
    n = worker.run()
    print(f"train worker {worker.worker_id} done: {n} trials", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
