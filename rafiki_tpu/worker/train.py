"""Train worker: the per-sub-mesh trial loop.

Parity target: the reference's ``worker/train.py`` (SURVEY.md §3.1): loop
until the advisor's budget is exhausted — get a proposal, build the model
template with the proposed knobs, train, evaluate, report the score, save
parameters. One worker per TPU sub-mesh replaces one container per GPU.

TPU-first deltas:
- The worker passes its sub-mesh devices into ``TrainContext`` so templates
  pjit over exactly the chips they own (device multi-tenancy, SURVEY.md §7).
- BOHB rung semantics ride the same loop: ``budget_scale`` scales epochs,
  ``warm_start_trial_id`` resumes a promoted trial from its own lower-rung
  checkpoint in the ParamStore.
- ``should_continue`` gives the advisor a per-epoch early-stop hook
  (preemption-friendly: the last completed epoch is always checkpointable).
"""

from __future__ import annotations

import math
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple, Type

from ..model.base import BaseModel, TrainContext
from ..model.log import ModelLogger
from ..obs import (MetricsRegistry, ObsServer, TraceBuffer,
                   mint_trace_id)
from ..store.param_store import ParamStore

#: substrings marking infra-class failures in exception text. The gRPC/XLA
#: status names cover the TPU runtime's device-loss vocabulary
#: (jaxlib raises XlaRuntimeError with "UNAVAILABLE: ..."-style messages);
#: "preempt" covers scheduler/maintenance-event wording.
_PREEMPTION_MARKERS = ("UNAVAILABLE", "RESOURCE_EXHAUSTED",
                       "DEADLINE_EXCEEDED", "DATA_LOSS", "ABORTED",
                       "preempt")


def classify_trial_error(e: BaseException) -> str:
    """``"preemption"`` (infra fault — resumable on healthy hardware) vs
    ``"deterministic"`` (code/knob bug — resume would reproduce the
    crash). Drives :meth:`MetaStore.claim_trial_for_resume` eligibility:
    only preemption-class ERRORED rows may be claimed by peers."""
    if isinstance(e, (FileNotFoundError, IsADirectoryError,
                      NotADirectoryError, PermissionError)):
        # path-shaped OSErrors are config bugs (wrong dataset path,
        # missing blob) — every peer would hit the identical error
        return "deterministic"
    if isinstance(e, (OSError, MemoryError, EOFError)):
        return "preemption"
    msg = f"{type(e).__name__}: {e}"
    if any(m in msg for m in _PREEMPTION_MARKERS):
        return "preemption"
    return "deterministic"


class TrainWorker:
    """Runs trials against an advisor (in-proc object or HTTP client —
    both expose propose/feedback/trial_errored)."""

    def __init__(self, model_class: Type[BaseModel], advisor: Any,
                 train_dataset_path: str, val_dataset_path: str,
                 param_store: Optional[ParamStore] = None,
                 meta_store: Optional[Any] = None,
                 sub_train_job_id: str = "", model_id: str = "",
                 devices: Optional[List[Any]] = None,
                 worker_id: str = "worker-0",
                 profile_dir: Optional[str] = None,
                 knob_overrides: Optional[dict] = None,
                 checkpoint_interval_s: float = 30.0) -> None:
        self.model_class = model_class
        self.advisor = advisor
        self.train_dataset_path = train_dataset_path
        self.val_dataset_path = val_dataset_path
        self.param_store = param_store or ParamStore()
        self.meta_store = meta_store
        self.sub_train_job_id = sub_train_job_id
        self.model_id = model_id
        self.devices = devices
        self.worker_id = worker_id
        self.profile_dir = profile_dir
        #: job-level knob pins (train_args["knob_overrides"]) merged over
        #: every proposal — how a job fixes e.g. max_len or batch_size
        #: regardless of what the advisor samples
        self.knob_overrides = dict(knob_overrides or {})
        #: min seconds between mid-trial checkpoints; <=0 disables them
        self.checkpoint_interval_s = checkpoint_interval_s
        #: liveness beacon period while a trial trains (threaded, so
        #: long epochs don't read as death)
        self.heartbeat_interval_s = 5.0
        #: a RUNNING trial with no heartbeat for this long is an orphan
        self.orphan_stale_s = 60.0
        #: lifetime cap on resumed orphans (bounds ping-pong when a
        #: resumed trial keeps crashing deterministically across workers)
        self.max_resumes = 16
        self._resumes_done = 0
        #: trial ids created by THIS process (self-resume exclusion that
        #: still lets a restarted worker reclaim its pre-restart orphan)
        self._own_trial_ids: set = set()
        self.trials_run = 0
        #: obs plane: per-trial wall/epoch timing + throughput so the
        #: advisor's trials become comparable on MORE than loss — the
        #: same registry/trace surfaces (/metrics, /debug/requests via
        #: serve_obs) every other service exposes
        self.metrics = MetricsRegistry()
        self.traces = TraceBuffer(256)
        self._h_trial = self.metrics.histogram(
            "trial_seconds", "trial wall time, train+eval (seconds)",
            buckets=(1, 2, 5, 10, 30, 60, 120, 300, 600, 1800, 3600,
                     7200, 14400))
        self._h_epoch = self.metrics.histogram(
            "epoch_seconds", "gap between epoch metric records",
            buckets=(0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60, 120, 300,
                     600, 1800))
        self._c_completed = self.metrics.counter(
            "trials_completed", "trials that finished with a score")
        self._c_errored = self.metrics.counter(
            "trials_errored", "trials that raised")
        self._g_tps = self.metrics.gauge(
            "last_trial_tokens_per_s",
            "token throughput of the last completed trial (LM only)")
        self._g_mfu = self.metrics.gauge(
            "last_trial_est_mfu",
            "estimated model-FLOPs utilization of the last trial")
        self._obs_server: Optional[ObsServer] = None

    def serve_obs(self, host: str = "127.0.0.1",
                  port: int = 0) -> Tuple[str, int]:
        """Start the observability sidecar (``GET /metrics``,
        ``GET /debug/requests`` — trial timelines) on a daemon thread."""
        self._obs_server = ObsServer(self.metrics, self.traces,
                                     host=host, port=port)
        return self._obs_server.start()

    def stop_obs(self) -> None:
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None

    # ---- one trial ----
    def run_trial(self, proposal) -> Optional[float]:
        from ..advisor.base import TrialResult

        from ..model.knob import shape_signature

        if self.knob_overrides:
            proposal.knobs = {**proposal.knobs, **self.knob_overrides}
        if proposal.meta.get("resumed_from") and \
                proposal.warm_start_trial_id and \
                "share_params" in self.model_class.get_knob_config():
            # AFTER the override merge: a job-level share_params pin must
            # not silently drop the resume's warm start (the reduced
            # budget only makes sense on top of the checkpoint)
            proposal.knobs = {**proposal.knobs, "share_params": True}
        # resumed trials: the row records the ORIGINAL budget_scale (so a
        # later re-resume computes remainders against the true total);
        # only the in-context scale is reduced by progress already made
        base_frac = float(proposal.meta.get("resume_frac_done") or 0.0)
        ctx_budget_scale = proposal.budget_scale * max(0.0, 1.0 - base_frac)
        if self.meta_store is not None:
            trial_id = self.meta_store.create_trial(
                self.sub_train_job_id, proposal.trial_no,
                model_id=self.model_id, knobs=proposal.knobs,
                worker_id=self.worker_id,
                budget_scale=proposal.budget_scale,
                shape_sig=shape_signature(
                    self.model_class.get_knob_config(), proposal.knobs))["id"]
        else:
            trial_id = f"{self.worker_id}-t{proposal.trial_no}"
        self._own_trial_ids.add(trial_id)

        logger = ModelLogger()
        obs_acc: Dict[str, Any] = {"tokens": 0, "epochs": 0,
                                   "last_t": None}

        def _sink(rec) -> None:
            # obs first (epoch timing / token accounting), then the
            # MetaStore forward the dashboard reads
            self._observe_log_record(rec, obs_acc)
            if self.meta_store is not None:
                self.meta_store.add_trial_log(trial_id, rec.kind,
                                              rec.data, rec.time)

        logger.sink = _sink
        t_start = time.monotonic()
        trace_id = self.traces.start(
            mint_trace_id(), request_id=trial_id, span="trial_start",
            trial_no=proposal.trial_no, worker=self.worker_id)

        # heartbeat covers the trial row's ENTIRE time in RUNNING state —
        # including the final (possibly multi-GB) parameter save — so a
        # live finishing trial can never look orphaned to a peer
        hb_stop = self._start_heartbeat(trial_id)
        try:
            try:
                self.model_class.validate_knobs(proposal.knobs)
                model = self.model_class(**proposal.knobs)
                self._admission_check(model)
                shared = None
                if proposal.warm_start_trial_id:
                    shared = self.param_store.load(
                        proposal.warm_start_trial_id)
                    if shared is None:
                        # big-model trials checkpoint SHARDED (SURVEY
                        # §5.4) — hand the template a lazy restore
                        # handle instead of assembling the tree here
                        shared = self.param_store.sharded_ref(
                            proposal.warm_start_trial_id)
                trial_profile_dir = None
                if self.profile_dir:
                    import os

                    trial_profile_dir = os.path.join(self.profile_dir,
                                                     trial_id)
                    os.makedirs(trial_profile_dir, exist_ok=True)
                ctx = TrainContext(devices=self.devices,
                                   budget_scale=ctx_budget_scale,
                                   shared_params=shared, logger=logger,
                                   trial_id=trial_id,
                                   profile_dir=trial_profile_dir)
                ckpt_key = f"ckpt-{trial_id}"
                if self.checkpoint_interval_s > 0:
                    self._wire_checkpointing(ctx, ckpt_key, base_frac,
                                             proposal, shared)
                if trial_profile_dir:
                    # per-trial jax.profiler trace (SURVEY.md §5.1):
                    # XLA/HLO timing + (on TPU) hardware counters,
                    # viewable in TensorBoard / Perfetto
                    import jax

                    with jax.profiler.trace(trial_profile_dir):
                        model.train(self.train_dataset_path, ctx)
                else:
                    model.train(self.train_dataset_path, ctx)
                score = float(model.evaluate(self.val_dataset_path))

                blob = model.dump_parameters()
                self._record_trial_obs(logger, trace_id, t_start,
                                       obs_acc, blob, score)
                self.param_store.save(trial_id, blob)
                model.destroy()
                fenced_out = False
                if self.meta_store is not None:
                    # fenced completion: False = a resume claimant already
                    # TERMINATED this row (we were presumed dead during a
                    # long stall) — our duplicate must NOT double-feed the
                    # advisor for this trial_no
                    fenced_out = not self.meta_store.mark_trial_completed(
                        trial_id, score, params_saved=True)
                try:
                    # cleanup is best-effort AFTER the terminal mark: a
                    # kv hiccup here must not void a finished trial
                    self.param_store.delete(ckpt_key)
                    self.param_store.delete(f"{ckpt_key}-meta")
                except Exception:  # rafiki: noqa[silent-except]
                    pass
                if not fenced_out:
                    try:
                        self.advisor.feedback(TrialResult(
                            trial_no=proposal.trial_no,
                            knobs=proposal.knobs,
                            score=score, trial_id=trial_id,
                            budget_scale=proposal.budget_scale,
                            meta=proposal.meta))
                    except Exception:  # noqa: BLE001
                        # a resumed trial may outlive its advisor's
                        # bracket state (advisor restarted with the
                        # stack); the score is already durable in the
                        # MetaStore, which is what deployment reads
                        if not proposal.meta.get("resumed_from"):
                            raise
                self.trials_run += 1
                return score
            except Exception as e:  # trial fault isolation (SURVEY §5.3)
                self._c_errored.inc()
                self.traces.add_span(trace_id, "trial_errored",
                                     error=f"{type(e).__name__}: {e}"[:200],
                                     error_class=classify_trial_error(e))
                fenced_out = False
                if self.meta_store is not None:
                    fenced_out = not self.meta_store.mark_trial_errored(
                        trial_id, f"{e}\n{traceback.format_exc()}",
                        error_class=classify_trial_error(e))
                if not fenced_out:
                    try:
                        self.advisor.trial_errored(proposal.trial_no)
                    except Exception:  # rafiki: noqa[silent-except]
                        # — a dead/restarted advisor must not kill the
                        # surviving worker; the error is durable in
                        # the MetaStore either way
                        pass
                return None
        finally:
            hb_stop()

    def _observe_log_record(self, rec, obs_acc: Dict[str, Any]) -> None:
        """Watch the trial's metric stream: every ``values`` record
        carrying a loss marks an epoch boundary — the inter-record gap
        is the live step-time signal — and templates that report a
        per-epoch ``tokens`` count (the LM loop does) accumulate it for
        throughput/MFU at trial end."""
        if rec.kind != "values" or "loss" not in rec.data:
            return
        now = time.monotonic()
        if obs_acc["last_t"] is not None:
            self._h_epoch.observe(now - obs_acc["last_t"])
        obs_acc["last_t"] = now
        obs_acc["epochs"] += 1
        tokens = rec.data.get("tokens")
        if isinstance(tokens, (int, float)) and tokens > 0:
            obs_acc["tokens"] += int(tokens)

    def _record_trial_obs(self, logger: ModelLogger, trace_id: str,
                          t_start: float, obs_acc: Dict[str, Any],
                          blob: Any, score: float) -> None:
        """Per-trial throughput record: wall seconds always; tokens/s
        and estimated MFU when the template reported per-epoch token
        counts (MFU ≈ 6·N·tokens/s over the device peak — the standard
        dense-LM approximation; an ESTIMATE, labeled as such). Logged
        through the trial's own logger so it lands in the MetaStore
        next to the loss curve — the advisor's trials become comparable
        on throughput, not just loss."""
        dt = time.monotonic() - t_start
        self._h_trial.observe(dt)
        self._c_completed.inc()
        vals: Dict[str, Any] = {"trial_seconds": round(dt, 3),
                                "epochs_logged": obs_acc["epochs"]}
        if obs_acc["tokens"] and dt > 0:
            tps = obs_acc["tokens"] / dt
            vals["tokens_per_s"] = round(tps, 1)
            self._g_tps.set(tps)
            n_params = _count_blob_params(blob)
            # tokens/s is FLEET-wide (the trial shards over this
            # worker's whole sub-mesh), so the denominator is the
            # sub-mesh's aggregate peak, not one chip's
            devs = self.devices
            if devs is None:
                try:
                    import jax

                    devs = jax.local_devices()
                except (ImportError, RuntimeError):
                    devs = None
            peak = _device_peak_flops(devs) * max(1, len(devs or ()))
            if n_params and peak:
                mfu = 6.0 * n_params * tps / peak
                vals["est_mfu"] = round(mfu, 5)
                self._g_mfu.set(mfu)
        try:
            logger.log(**vals)
        except Exception:  # noqa: BLE001 — a meta-store hiccup on the
            import logging  # throughput record must not void the trial

            logging.getLogger(__name__).warning(
                "trial throughput record failed", exc_info=True)
        self.traces.add_span(trace_id, "trial_done",
                             score=round(score, 6), **vals)

    def _admission_check(self, model) -> None:
        """Refuse a trial whose ESTIMATED per-device train footprint
        exceeds the chips' HBM, before any compile/allocation — an OOM
        mid-trial wastes the whole slot and reads as a mystery fault.

        Templates opt in by exposing ``estimate_device_budget(n) ->
        {..., "total": bytes}`` (the Llama template computes it from
        real shape math — ``estimate_train_device_bytes``). The limit
        comes from the accelerator's own ``memory_stats()["bytes_limit"]``
        (TPU/GPU) or the ``RAFIKI_DEVICE_HBM_BYTES`` env override (CPU
        runs have elastic host memory, so without the override the
        check is skipped there). A refusal raises ValueError — a
        deterministic-class trial error (resume would refuse again)."""
        est = getattr(model, "estimate_device_budget", None)
        if est is None:
            return
        import jax

        from .admission import resolve_device_limit

        devs = self.devices or jax.local_devices()
        limit = resolve_device_limit(devs)
        if not limit:
            return
        try:
            budget = est(len(devs))
            total = int(budget["total"])
        except Exception as e:  # an estimator bug must never block an
            # admissible trial — but it must be VISIBLE: silently
            # skipping here disables train admission control
            # fleet-wide until trials start OOMing (ADVICE.md r5)
            import logging

            logging.getLogger(__name__).warning(
                "train admission check skipped: "
                "estimate_device_budget raised %r", e, exc_info=True)
            return
        if total > limit:
            raise ValueError(
                "admission control: estimated "
                f"{total / 2**30:.2f}GiB/device train footprint "
                f"exceeds the {limit / 2**30:.2f}GiB device limit "
                f"(breakdown: { {k: round(v / 2**30, 2) for k, v in budget.items()} } GiB); "
                "shrink batch_size/max_len or enable remat/loss_chunk/"
                "grad_accum/model_parallel")

    def _wire_checkpointing(self, ctx, ckpt_key: str, base_frac: float,
                            proposal, shared) -> None:
        """Attach throttled epoch-boundary checkpointing to ``ctx``.

        The blob factory only runs when a save actually happens.
        ``frac_done`` rides in a tiny sidecar entry (NOT inside the blob —
        warm-start consumers expect ``dump_parameters()``'s exact shape)
        and is always GLOBAL progress: a resumed trial's template reports
        fractions of its REMAINING budget, which are mapped back onto the
        original total so chained resumes stay correct.

        A resumed trial is also pre-seeded with the orphan's checkpoint
        under its OWN key, so if this attempt dies before its first
        throttled save, the warm state is still reachable from this
        trial's row (the orphan's row is already TERMINATED and will
        never be scanned again)."""
        import time as _time

        if proposal.meta.get("resumed_from") and shared is not None:
            # bytes-level copy: no msgpack re-encode of a possibly
            # multi-GB tree that was deserialized moments ago (sharded
            # checkpoints copy at the directory level)
            if not self.param_store.copy(proposal.warm_start_trial_id,
                                         ckpt_key):
                self.param_store.copy_sharded(
                    proposal.warm_start_trial_id, ckpt_key)
            if base_frac > 0:
                self.param_store.save(f"{ckpt_key}-meta",
                                      {"frac_done": base_frac})

        last_save = [_time.monotonic()]

        def save_checkpoint(make_blob, frac_done=None, tree=None) -> None:
            """``tree`` (optional): the template's LIVE (sharded device)
            pytree — saved per-shard + async when the store supports it,
            so no host materializes the full tree (SURVEY §5.4); without
            it (or on mem/kv backends) the zero-arg ``make_blob``
            whole-tree path runs as before."""
            now = _time.monotonic()
            if now - last_save[0] < self.checkpoint_interval_s:
                return
            if tree is None or \
                    not self.param_store.save_sharded_async(ckpt_key,
                                                            tree):
                self.param_store.save(ckpt_key, make_blob())
            if frac_done is not None:
                global_frac = base_frac + float(frac_done) * (1 - base_frac)
                self.param_store.save(f"{ckpt_key}-meta",
                                      {"frac_done": global_frac})
            last_save[0] = now

        ctx.checkpoint = save_checkpoint

    def _start_heartbeat(self, trial_id: str):
        """Stamp the trial row every few seconds while training so peers
        can tell a preempted trial from a live slow one. Returns a
        stopper."""
        if self.meta_store is None:
            return lambda: None
        import threading

        stop = threading.Event()

        def beat() -> None:
            while not stop.wait(self.heartbeat_interval_s):
                try:
                    self.meta_store.heartbeat_trial(trial_id)
                except Exception:  # rafiki: noqa[silent-except]
                    pass           # never kill the trial

        t = threading.Thread(target=beat, daemon=True,
                             name=f"hb-{trial_id[:8]}")
        t.start()
        return stop.set

    # ---- preemption recovery ----
    def resume_orphaned_trials(self) -> int:
        """Finish trials a dead worker left behind (SURVEY.md §5.3).

        Orphan = status ERRORED with ``error_class='preemption'`` (infra
        fault recorded by a live worker — device loss, OOM), or RUNNING
        with a stale heartbeat, i.e. process death (a live owner stamps
        every ``heartbeat_interval_s``; the staleness test is enforced
        INSIDE the atomic claim, so a live peer's trial cannot be
        hijacked and exactly one claimant wins). Deterministic ERRORED
        rows — a code/knob crash — are never resumed: re-running them
        reproduces the crash (ADVICE r3 medium). With a ``ckpt-<id>``
        blob the trial resumes warm under the same knobs and trial_no,
        training only the remaining budget recorded at checkpoint time;
        without one (killed before the first throttled save) it re-runs
        cold — either way no zombie RUNNING rows remain.
        """
        if self.meta_store is None or self._resumes_done >= self.max_resumes:
            return 0
        import json as _json

        from ..advisor.base import Proposal

        n = 0
        for t in self.meta_store.get_trials_of_sub_train_job(
                self.sub_train_job_id):
            if t["status"] not in ("RUNNING", "ERRORED"):
                continue
            if t["status"] == "ERRORED" and \
                    t.get("error_class") != "preemption":
                continue  # deterministic crash — the claim would refuse
                # anyway; skip the doomed UPDATE round-trip
            if t["id"] in self._own_trial_ids:
                # trials from THIS process's lifetime: own failures are
                # code errors, not preemption, and a worker must never
                # loop resuming its own deterministic crash. (Keyed by
                # trial id, not worker_id — a RESTARTED worker with the
                # same deterministic name has an empty set and correctly
                # reclaims its pre-restart orphan.)
                continue
            if self._resumes_done >= self.max_resumes:
                break  # bound cross-worker ping-pong on persistent bugs
            if not self.meta_store.claim_trial_for_resume(
                    t["id"], self.worker_id,
                    stale_after_s=self.orphan_stale_s):
                continue  # live heartbeat, or another worker won
            ckpt_key = f"ckpt-{t['id']}"
            has_ckpt = self.param_store.exists(ckpt_key) or \
                self.param_store.exists_sharded(ckpt_key)
            frac = 0.0
            if has_ckpt:
                meta = self.param_store.load(f"{ckpt_key}-meta")
                if meta and meta.get("frac_done"):
                    frac = float(meta["frac_done"])
            knobs = t["knobs"]
            if isinstance(knobs, str):
                knobs = _json.loads(knobs)
            # the new row keeps the ORIGINAL budget_scale; run_trial
            # reduces only the in-context budget by frac and pre-seeds
            # the new trial's own checkpoint from the orphan's, so a
            # crashed resume is itself resumable at the right progress
            score = self.run_trial(Proposal(
                trial_no=int(t["trial_no"]), knobs=knobs,
                budget_scale=float(t["budget_scale"] or 1.0),
                warm_start_trial_id=ckpt_key if has_ckpt else "",
                meta={"resumed_from": t["id"],
                      "resume_frac_done": frac}))
            if score is not None:
                # delete the orphan's blob only on a COMPLETED resume: a
                # failed attempt may have died before the pre-seed copied
                # it, and this TERMINATED row's ckpt is then the only
                # warm state left (a successful pre-seed makes it merely
                # redundant — a bounded, harmless leak on failure)
                try:
                    self.param_store.delete(ckpt_key)
                    self.param_store.delete(f"{ckpt_key}-meta")
                except Exception:  # rafiki: noqa[silent-except]
                    pass  # cleanup must never kill the worker loop
            self._resumes_done += 1
            n += 1
        return n

    # ---- gang trial mode (rafiki_tpu/tuning) ----
    def run_gang(self, gang_size: int,
                 max_trials: Optional[int] = None) -> int:
        """Gang-compiled trial mode for small-zoo templates: K proposals
        train as K lanes of one vmapped jit step (one compile per static
        knob bucket), the advisor is driven through its batched verbs,
        and ASHA rungs cull lanes in place. Reports one TrialResult per
        lane (MetaStore row + ParamStore blob each, so deployment and
        the dashboard see gang trials exactly like process trials) and
        publishes ``gang_lanes_active`` / ``gang_lanes_culled_total`` /
        ``trials_per_hour`` / ``gang_samples_per_s`` through this
        worker's ObsServer. Falls back to the process loop for templates
        without a gang spec."""
        from ..model.knob import shape_signature
        from ..tuning import GangEngine, supports_gang

        if not supports_gang(self.model_class):
            import logging

            logging.getLogger(__name__).warning(
                "%s has no gang spec; gang_size=%d ignored, running "
                "sequential trials", self.model_class.__name__, gang_size)
            return self.run(max_trials)

        knob_config = self.model_class.get_knob_config()

        def on_result(result, blob) -> None:
            trial_id = result.trial_id
            if self.meta_store is not None:
                row = self.meta_store.create_trial(
                    self.sub_train_job_id, result.trial_no,
                    model_id=self.model_id, knobs=result.knobs,
                    worker_id=self.worker_id,
                    budget_scale=result.budget_scale,
                    shape_sig=shape_signature(knob_config, result.knobs))
                trial_id = row["id"]
            self.param_store.save(trial_id, blob)
            if self.meta_store is not None:
                self.meta_store.mark_trial_completed(
                    trial_id, result.score, params_saved=True)
            self._c_completed.inc()
            self.trials_run += 1

        def admission_check(knobs, k) -> Optional[str]:
            """HBM admission for one gang bucket: the whole gang is ONE
            program on one device slot, so the estimate must cover K
            adapter/optimizer lanes plus the broadcast base — with the
            bucket's ``remat_policy`` trading activation bytes for
            recompute (why a denied bucket can re-admit at
            remat_policy="full"). Returns a refusal reason (the bucket
            then runs sequentially, each trial re-checked by the
            per-trial admission gate) or None to admit."""
            import jax

            from .admission import resolve_device_limit

            devs = self.devices or jax.local_devices()
            limit = resolve_device_limit(devs)
            if not limit:
                return None
            model = self.model_class(**knobs)
            est = getattr(model, "estimate_device_budget", None)
            if est is None:
                return None
            try:
                try:
                    budget = est(len(devs), gang_size=k)
                except TypeError:
                    return None  # estimator predates gang budgets
                total = int(budget["total"])
            except Exception as e:  # estimator bug: visible, not fatal
                import logging

                logging.getLogger(__name__).warning(
                    "gang admission check skipped: "
                    "estimate_device_budget raised %r", e, exc_info=True)
                return None
            if total > limit:
                gib = {key: round(v / 2**30, 2)
                       for key, v in budget.items()}
                return (f"estimated {total / 2**30:.2f}GiB footprint for "
                        f"a {k}-lane gang exceeds the "
                        f"{limit / 2**30:.2f}GiB device limit "
                        f"(breakdown: {gib} GiB); set remat_policy="
                        "'full'/'policy' to trade activation HBM for "
                        "recompute, or shrink the gang")
            return None

        engine = GangEngine(
            self.model_class, self.advisor, self.train_dataset_path,
            self.val_dataset_path, gang_size=gang_size, mode="gang",
            knob_overrides=self.knob_overrides, metrics=self.metrics,
            on_result=on_result, admission_check=admission_check)
        self.gang_engine = engine  # introspection: buckets, refusals
        results = engine.run(max_trials)
        return len(results)

    # ---- the loop ----
    def run(self, max_trials: Optional[int] = None) -> int:
        """Pull proposals until the advisor says stop; returns #trials.

        Orphan pickup happens at startup, between proposals, AND in a
        bounded linger after the advisor is exhausted — a peer preempted
        moments ago has a trial that only turns claimably stale after
        ``orphan_stale_s``, and exiting immediately would strand it as a
        zombie the job finalizer can't resolve.
        """
        n = self.resume_orphaned_trials()
        while max_trials is None or n < max_trials:
            proposal = self.advisor.propose()
            if not proposal.is_valid:
                break
            self.run_trial(proposal)
            n += 1
            n += self.resume_orphaned_trials()
        n += self._linger_for_orphans()
        return n

    def _linger_for_orphans(self) -> int:
        """Wait (bounded) for peers' RUNNING trials to either finish or
        turn stale, resuming any that do. A live peer ends the linger
        early by completing; a dead one becomes claimable within
        ``orphan_stale_s``."""
        if self.meta_store is None:
            return 0
        import time as _time

        deadline = _time.monotonic() + self.orphan_stale_s \
            + 2 * self.heartbeat_interval_s
        n = 0
        while _time.monotonic() < deadline:
            # "not mine" = not created by THIS process — a respawned
            # replacement shares its dead predecessor's worker_id, and
            # the predecessor's mid-flight trial is exactly what it is
            # here to pick up
            peers_running = any(
                t["status"] == "RUNNING"
                and t["id"] not in self._own_trial_ids
                for t in self.meta_store.get_trials_of_sub_train_job(
                    self.sub_train_job_id))
            if not peers_running:
                break
            n += self.resume_orphaned_trials()
            _time.sleep(min(2.0, self.heartbeat_interval_s))
        return n


def _count_blob_params(blob: Any) -> int:
    """Leaf-element count of a dumped parameter tree (numpy arrays in
    nested dicts/lists) — no jax import needed."""
    if hasattr(blob, "shape"):
        try:
            return int(math.prod(blob.shape))
        except (TypeError, ValueError):
            return 0
    if isinstance(blob, dict):
        return sum(_count_blob_params(v) for v in blob.values())
    if isinstance(blob, (list, tuple)):
        return sum(_count_blob_params(v) for v in blob)
    return 0


#: bf16 peak FLOP/s per chip by device_kind substring (first match
#: wins, so the more specific names come first). Used only for the
#: est_mfu label — an estimate feeding trial comparisons, not billing.
_PEAK_FLOPS_BF16 = (
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


def _device_peak_flops(devices: Optional[List[Any]] = None) -> float:
    """Per-device peak FLOP/s: the ``RAFIKI_DEVICE_PEAK_FLOPS`` env
    override wins (how CPU runs get a nonzero MFU denominator in
    tests), else a device_kind lookup; unknown hardware → 0, which
    suppresses the MFU estimate rather than fabricating one."""
    import os

    env = os.environ.get("RAFIKI_DEVICE_PEAK_FLOPS", "")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            return 0.0
    try:
        if devices is None:
            import jax

            devices = jax.local_devices()
        kind = str(getattr(devices[0], "device_kind", "") or "").lower()
    except (ImportError, IndexError, RuntimeError):
        return 0.0
    for key, flops in _PEAK_FLOPS_BF16:
        if key in kind:
            return flops
    return 0.0


def main(argv: Optional[list] = None) -> int:
    """Service entrypoint: ``python -m rafiki_tpu.worker.train``.

    Spawned by the ServicesManager with a JSON config file; connects to the
    advisor service over HTTP and to the shared stores.
    """
    import argparse
    import json

    from ..parallel.multihost import initialize_from_env
    from ..utils.platform import apply_platform_env

    apply_platform_env()  # before any jax backend initializes
    initialize_from_env()  # multi-host rendezvous (no-op if unconfigured)

    from ..advisor.service import AdvisorClient
    from ..model.base import load_model_class
    from ..store.meta_store import MetaStore

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True,
                        help="JSON: {advisor_url, model_file, model_class, "
                             "train_dataset, val_dataset, param_store_uri, "
                             "meta_store_path, sub_train_job_id, worker_id}")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)

    with open(cfg["model_file"], "rb") as f:
        model_class = load_model_class(f.read(), cfg["model_class"])
    meta_store = (MetaStore(cfg["meta_store_path"])
                  if cfg.get("meta_store_path") else None)
    worker = TrainWorker(
        model_class=model_class,
        advisor=AdvisorClient(cfg["advisor_url"]),
        train_dataset_path=cfg["train_dataset"],
        val_dataset_path=cfg["val_dataset"],
        param_store=ParamStore.from_uri(cfg.get("param_store_uri", "mem://")),
        meta_store=meta_store,
        sub_train_job_id=cfg.get("sub_train_job_id", ""),
        model_id=cfg.get("model_id", ""),
        worker_id=cfg.get("worker_id", "worker-0"),
        profile_dir=cfg.get("profile_dir"),
        knob_overrides=cfg.get("knob_overrides"),
        checkpoint_interval_s=float(
            cfg.get("checkpoint_interval_s", 30.0)))
    # observability sidecar: /metrics (trial/epoch timing, MFU gauges)
    # + /debug/requests (per-trial timelines)
    obs_host, obs_port = worker.serve_obs(
        cfg.get("obs_host", "127.0.0.1"), int(cfg.get("obs_port", 0)))
    if cfg.get("obs_port_file"):
        with open(cfg["obs_port_file"], "w") as f:
            f.write(str(obs_port))
    print(f"train worker {worker.worker_id} obs on "
          f"{obs_host}:{obs_port}", flush=True)
    try:
        gang_size = int(cfg.get("gang_size") or 0)
        n = worker.run_gang(gang_size) if gang_size >= 1 else worker.run()
    finally:
        worker.stop_obs()
    print(f"train worker {worker.worker_id} done: {n} trials", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
