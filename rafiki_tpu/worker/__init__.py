"""Workers: the per-sub-mesh trial loop and serving replicas."""

from .inference import InferenceWorker
from .train import TrainWorker

__all__ = ["TrainWorker", "InferenceWorker"]
