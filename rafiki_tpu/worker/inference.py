"""Inference worker: one serving replica of a best trial.

Parity target: the reference's ``worker/inference.py`` (SURVEY.md §3.3):
boot by loading a trial's parameters from the ParamStore, then loop —
block-pop the query queue, batch what's pending, run ``model.predict``,
push predictions keyed by query id.

TPU-first deltas:

- **Opportunistic micro-batching** (classification path): after a
  blocking pop the worker drains whatever else is queued (up to
  ``max_batch_msgs``) and runs one forward over the union — on TPU the
  forward is a compiled program whose cost is dominated by launch + HBM
  traffic, so batching waiting queries is nearly free throughput.
  Static-shape padding happens inside the template's ``predict``
  (bucketed), not here.
- **Continuous-batching decode loop** (generation path, BASELINE.md
  config #5): when constructed with ``decode_loop=True`` and the model
  exposes ``make_decode_engine`` (e.g. ``LlamaLoRA``), the worker runs
  a slot-based decode loop instead — new requests are admitted into
  free KV-cache slots at step boundaries while earlier requests are
  mid-generation, and replies go out per-message as each message's
  queries all complete.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple, Type

import numpy as np

from ..model.base import BaseModel
from ..obs import (MetricsRegistry, ObsServer, StatsMap, TraceBuffer,
                   mint_trace_id)
from ..serving.kv_transfer import normalize_role
from ..serving.queues import (EXPIRY_SKEW_TOLERANCE_S, QueueHub,
                              pack_message, unpack_message)
from ..serving.slo import SLO_CLASSES, normalize_slo
from ..store.param_store import ParamStore

#: expiry pad for the RELATIVE (ttl_s) deadline path: the residual
#: error there is the skew-estimator's convergence slack, not raw
#: cross-host clock skew, so it is a fraction of the wall-clock
#: EXPIRY_SKEW_TOLERANCE_S it replaces
TTL_EXPIRY_PAD_S = 0.5

#: prefill-role outbox give-up window: generous enough for the
#: slowest chunked prefill to finish and ship, small enough that
#: never-completing legs (engine reset dropped the slot) can't grow
#: the outbox unboundedly on a long-lived worker. A pruned leg's
#: decode side re-prefilled locally when ITS (much shorter) kv_wait_s
#: window expired — pruning loses nothing.
_KV_OUTBOX_TTL_S = 600.0


class ClockSkewEstimator:
    """Skew-compensated elapsed time since a remote wall-clock stamp.

    Every scatter payload carries ``sent_ts`` (the predictor's wall
    clock at scatter). ``now - sent_ts`` observed here is *true elapsed
    + clock skew*; since elapsed is never negative and promptly-popped
    queries have near-zero elapsed, the MINIMUM of those observations
    converges on the skew itself (one-way-delay estimation, the NTP
    trick). Subtracting it yields an elapsed estimate that is immune to
    static cross-host skew — the failure mode where a worker clock
    running ahead silently dropped every fresh query while the
    predictor only saw timeouts (ADVICE r3). The estimate relaxes
    upward very slowly so a mid-run clock step eventually re-converges
    instead of poisoning the minimum forever."""

    #: upward relaxation per observation (dimensionless fraction of the
    #: gap): ~460 observations to close 99% of a step — minutes of
    #: traffic, versus never
    RELAX = 0.01

    def __init__(self) -> None:
        self._est: Optional[float] = None

    def elapsed_since(self, sent_ts: float) -> float:
        obs = time.time() - float(sent_ts)  # true elapsed + skew
        if self._est is None or obs < self._est:
            self._est = obs
        else:
            self._est += self.RELAX * (obs - self._est)
        return obs - self._est


class InferenceWorker:
    def __init__(self, model_class: Type[BaseModel], trial_id: str,
                 knobs: dict, param_store: ParamStore, hub: QueueHub,
                 worker_id: str, max_batch_msgs: int = 16,
                 decode_loop: bool = False, max_slots: int = 8,
                 max_new_tokens: int = 8, steps_per_sync: int = 4,
                 speculate_k: int = 0, system_prefix: str = "",
                 extra_adapter_trials: Optional[List[str]] = None,
                 draft_trial_id: str = "",
                 draft_knobs: Optional[dict] = None,
                 kv_page_size: int = 0, kv_pages: int = 0,
                 paged_kernel: Optional[bool] = None,
                 default_slo: str = "",
                 role: str = "", host_kv_pages: int = 0,
                 kv_wait_s: float = 1.5, pool_id: str = "",
                 chaos: Optional[Any] = None) -> None:
        self.worker_id = worker_id
        self.hub = hub
        self.max_batch_msgs = max_batch_msgs
        #: disaggregated serving role (``unified`` default): a
        #: ``prefill`` worker chews prompts through chunked prefill and
        #: ships the finished KV pages to the decode leg's worker over
        #: the hub; a ``decode`` worker holds shipped-KV requests for
        #: up to ``kv_wait_s`` and installs the blob at admission —
        #: falling back to a local re-prefill (token-exact, just
        #: slower) when the shipment is late, lost, or mismatched.
        #: Validated at boot: a typo'd role silently serving unified
        #: would defeat the router's placement policy.
        self.role = normalize_role(role)
        self.kv_wait_s = max(0.0, float(kv_wait_s))
        #: the job's pool id (scale-out plane): keys the shared
        #: prefix-snapshot blob so one replica's prefill serves all
        self.pool_id = str(pool_id or "")
        #: decode-role holding pen: message id -> (message, monotonic
        #: give-up deadline, {qi: blob}) — submitted when every
        #: query's shipment lands or the wait window expires
        self._pending_kv: Dict[Any, List[Any]] = {}
        #: prefill-role outbox: message id -> [ship-to worker id,
        #: trace id, queries still owed, monotonic give-up deadline];
        #: poll_kv completions are forwarded against it and decrement
        #: the owed count — the entry dies at zero, or at the deadline
        #: for legs whose slots never produce a blob (engine reset,
        #: preemption), so a long-lived prefill worker's outbox stays
        #: bounded by in-flight legs instead of growing per message
        self._kv_outbox: Dict[Any, List[Any]] = {}
        #: flipped by the first held shipped-KV request: from then on
        #: the pump keeps draining the shipment queue even with
        #: nothing pending, so late blobs for already-admitted
        #: requests don't accumulate; workers that never see
        #: disaggregated traffic skip the drain entirely
        self._kv_seen_traffic = False
        #: admission class applied to requests that carry no ``slo``
        #: of their own (the per-job default; per-request override
        #: rides the scatter payload). Validated at boot: a typo'd
        #: job default must fail the deploy, not degrade silently.
        self.default_slo = normalize_slo(default_slo)
        #: visible drop accounting: silent expiry drops look identical to
        #: gather timeouts from the predictor side, so the worker keeps
        #: its own count (and logs) — the first diagnostic to check when
        #: "the predictor only sees timeouts" (clock skew, ADVICE r3).
        #: drain_rejected counts messages error-replied while draining.
        self.stats = StatsMap({"dropped_expired": 0,
                               "drain_rejected": 0,
                               # disaggregated prefill/decode: blobs
                               # shipped out (prefill role), installed
                               # from the wire (decode role), and the
                               # degradations — wait window expired /
                               # blob rejected → local re-prefill
                               "kv_ships_sent": 0,
                               "kv_imports_installed": 0,
                               "kv_wait_timeouts": 0,
                               "kv_import_fallbacks": 0,
                               # data-plane survival: 1 while the hub
                               # is unreachable past the reconnect
                               # window (the serve loop PAUSES — obs
                               # sidecar keeps answering); outages
                               # counts distinct pause episodes
                               "data_plane_down": 0,
                               "hub_outages": 0})
        self._dp_down = False
        #: deterministic fault injection (tests / chaos drills): either
        #: passed programmatically or armed via the RAFIKI_CHAOS env
        #: var; when armed, queue-level faults ride a ChaosHub wrapper
        #: and the kill-after-N-tokens trigger is checked in the decode
        #: loop. None (the default) costs nothing.
        if chaos is None:
            from ..chaos import ChaosConfig, ChaosInjector

            cfg = ChaosConfig.from_env()
            chaos = ChaosInjector(cfg) if cfg is not None else None
        self.chaos = chaos
        self.chaos_killed = False
        if self.chaos is not None:
            from ..chaos import ChaosHub

            self.hub = ChaosHub(hub, self.chaos)
        #: graceful drain: set via POST /drain on the obs sidecar or a
        #: {"control": "drain"} queue message — stop admitting, finish
        #: in-flight streams, publish `draining`, then exit the loop
        self._draining = threading.Event()
        #: skew-compensated expiry clock for the relative ttl_s
        #: deadlines (wall deadline_ts stays as the fallback)
        self._skew = ClockSkewEstimator()
        #: the obs plane: registry scraped at GET /metrics (serve_obs
        #: sidecar), trace ring at GET /debug/requests, and the request-
        #: lifecycle histograms the engine's span hook feeds
        self.metrics = MetricsRegistry()
        self.metrics.register_stats(self.stats)
        # hub reconnect/retry counters from the shared kv client layer
        # (hub_reconnects_total / hub_rpc_retries_total): the worker's
        # /metrics shows how hard the data plane made it work
        from ..native.client import CLIENT_STATS as _kv_client_stats

        self.metrics.register_stats(_kv_client_stats)
        if self.chaos is not None:
            # injected faults are observable, not a mystery: chaos_*
            # gauges ride the worker's /metrics like any counter
            self.metrics.register_stats(self.chaos.counters,
                                        prefix="chaos_")
        self.traces = TraceBuffer(512)
        self._boot_mono = time.monotonic()
        self._h_ttft = self.metrics.histogram(
            "ttft_seconds", "queued -> first generated token (seconds)")
        self._h_queue = self.metrics.histogram(
            "time_in_queue_seconds",
            "queued -> decode-slot admission (seconds)")
        self._h_e2e = self.metrics.histogram(
            "request_seconds",
            "queued -> request fully answered (seconds)")
        self._h_occupancy = self.metrics.histogram(
            "batch_occupancy", "live decode slots per engine step",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
        self._h_tps = self.metrics.histogram(
            "decode_tokens_per_s",
            "per-request generated-token throughput",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                     5000))
        self._h_step = self.metrics.histogram(
            "decode_step_seconds",
            "one fused engine step() — admission + K decode tokens "
            "(seconds); read next to paged_kernel_mode to see the "
            "kernel-vs-gather difference on a live worker")
        self._h_kv_transfer = self.metrics.histogram(
            "kv_transfer_seconds",
            "one host-tier page transfer (evict d2h or prefetch "
            "staging) on the tier thread (seconds); persistently large"
            " values mean the tier thrashes — grow HBM pages or shrink"
            " host_kv_pages",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
        # class-labeled latency histograms: the brownout ladder feeds
        # on the INTERACTIVE p95 alone, and an SLO story without
        # per-class latency evidence is unverifiable. Same metric
        # names, a `slo` label per class (the registry keys on
        # (name, labels)); the published per-class p95 gauges below
        # are what the predictor's controller actually reads.
        self._h_ttft_slo = {
            c: self.metrics.histogram(
                "ttft_seconds",
                "queued -> first generated token (seconds)",
                labels={"slo": c}) for c in SLO_CLASSES}
        self._h_e2e_slo = {
            c: self.metrics.histogram(
                "request_seconds",
                "queued -> request fully answered (seconds)",
                labels={"slo": c}) for c in SLO_CLASSES}
        # bounded per-class (timestamp, sample) windows backing the
        # PUBLISHED p95 gauges: the brownout ladder must see recovery,
        # and a lifetime-cumulative histogram quantile stays polluted
        # by an ended overload for hours (fast samples would need to
        # outnumber slow ones ~19:1 before the p95 moves). Samples
        # also age out by TIME (publish-side prune): when interactive
        # traffic stops entirely, the window must drain to empty —
        # read as cooling — instead of pinning the ladder at the last
        # overload's p95 all night. The labeled histograms above keep
        # the cumulative /metrics view.
        self._slo_ttft_win = {c: collections.deque(maxlen=256)
                              for c in SLO_CLASSES}
        self._slo_e2e_win = {c: collections.deque(maxlen=256)
                             for c in SLO_CLASSES}
        #: appends run on the serve-loop thread, but _publish_stats
        #: also runs on the obs sidecar thread (POST /drain publishes
        #: immediately) — and _window_p95 both prunes and iterates,
        #: so the windows need their own lock like every other
        #: cross-thread read in this file
        self._slo_win_lock = threading.Lock()
        #: engine request id -> (trace_id, queued monotonic, slo).
        #: Touched only by the serve-loop thread (submits, step, span
        #: hook all run there), so no lock
        self._req_obs: Dict[Any, Tuple[str, float, str]] = {}
        self._obs_server: Optional[ObsServer] = None
        self._obs_port = 0
        self._stop = threading.Event()
        self.model = model_class(**knobs)
        params = param_store.load(trial_id)
        if params is None:
            raise KeyError(f"no parameters for trial {trial_id!r}")
        self.model.load_parameters(params)
        # an (unloaded) draft twin sized from its knobs: its params +
        # cache count toward admission via the estimator's eval_shape
        # path, BEFORE any blob loads or engine builds
        draft_for_admission = None
        if draft_trial_id and decode_loop and speculate_k >= 2:
            draft_for_admission = model_class(**(draft_knobs or knobs))
        if host_kv_pages and not (decode_loop and kv_page_size):
            raise ValueError(
                "host_kv_pages requires decode_loop and kv_page_size "
                "> 0 (the host tier spills KV PAGES)")
        #: cross-worker prefix sharing: when a pool peer already
        #: published the shared prefix's KV snapshot, SKIP the local
        #: prefix prefill (build without system_prefix) and import the
        #: blob after boot — prefilled once per pool, not per replica.
        #: Single-adapter deployments only (per-adapter snapshots stay
        #: per-worker); best-effort — a hub hiccup just re-prefills.
        self._peer_prefix_blob: Optional[dict] = None
        self._system_prefix = str(system_prefix or "")
        if self.pool_id and system_prefix and decode_loop \
                and not extra_adapter_trials:
            try:
                raw = self.hub.get_blob(f"prefix:{self.pool_id}:0")
                if raw is not None:
                    self._peer_prefix_blob = unpack_message(raw)
                    system_prefix = ""  # peer's snapshot replaces the
                    #                     local prefix prefill entirely
            except Exception:  # rafiki: noqa[silent-except] — sharing
                pass           # is an optimization, never a boot gate
        if self.role != "unified" and not decode_loop:
            raise ValueError(
                f"worker role {self.role!r} requires decode_loop: the "
                "micro-batch path has no KV to disaggregate")
        self._admission_check(
            max_slots if decode_loop else 0,
            len(extra_adapter_trials or ()) if decode_loop else 0,
            draft_for_admission,
            kv_page_size=kv_page_size if decode_loop else 0,
            kv_pages=kv_pages if decode_loop else 0,
            host_kv_pages=host_kv_pages if decode_loop else 0)
        self.engine = None
        if draft_trial_id and (not decode_loop or speculate_k < 2):
            # fail loudly, like the multi-adapter misconfigurations: an
            # operator who named a draft trial believes speculation is
            # live — silently serving without it hides the mistake
            raise ValueError(
                "draft_trial_id requires decode_loop and "
                f"speculate_k >= 2 (got speculate_k={speculate_k})")
        if draft_trial_id and extra_adapter_trials:
            raise ValueError(
                "draft_trial_id is not supported with multi-adapter "
                "deployment (the stacked engine has no draft path)")
        if decode_loop and extra_adapter_trials:
            if not hasattr(self.model, "make_multi_adapter_engine"):
                # fail LOUDLY: falling back to a single-adapter engine
                # would route every adapter_id to the primary trial —
                # the wrong-tenant answer multi-adapter validation
                # exists to prevent
                raise RuntimeError(
                    f"{model_class.__name__} does not support "
                    "multi-adapter serving (no make_multi_adapter_"
                    "engine); deploy plain replicas instead")
            # multi-adapter deployment: this worker serves the PRIMARY
            # trial as adapter 0 and each extra trial as adapter 1..N —
            # one base model's HBM, one compiled step, requests routed
            # by sampling={"adapter_id": i}. The trials must share
            # every non-adapter leaf (adapters_only training); the
            # stacking validation below fails the boot loudly otherwise
            trees = [self.model._params]
            for tid in extra_adapter_trials:
                dump = param_store.load(tid)
                if dump is None:
                    raise KeyError(
                        f"no parameters for adapter trial {tid!r}")
                peer = model_class(**knobs)
                peer.load_parameters(dump)
                trees.append(peer._params)
            extra = {}
            if kv_page_size:  # only ride when set: user templates that
                # predate paged KV keep working at the defaults
                extra = {"kv_page_size": kv_page_size,
                         "kv_pages": kv_pages}
                if paged_kernel is not None:
                    extra["paged_kernel"] = bool(paged_kernel)
                if host_kv_pages:
                    extra["host_kv_pages"] = int(host_kv_pages)
            try:
                self.engine = self.model.make_multi_adapter_engine(
                    trees, max_slots=max_slots,
                    max_new_tokens=max_new_tokens,
                    steps_per_sync=steps_per_sync,
                    speculate_k=speculate_k, **extra)
            except ValueError as e:
                raise RuntimeError(
                    "multi-adapter deployment requires trials that "
                    "share one base (train them with adapters_only=True"
                    " and identical shape-relevant knobs); deploy as "
                    f"plain replicas instead: {e}") from e
            if system_prefix:
                # per-adapter snapshots: the prefix KV is a function of
                # the adapter that computed it, so every tenant gets
                # its own (same text, N different KV caches)
                for aid in range(len(trees)):
                    self.engine.register_prefix(system_prefix,
                                                adapter_id=aid)
        elif decode_loop:
            if hasattr(self.model, "make_decode_engine"):
                # optional kwargs only ride when set: user templates
                # that predate them keep working at the defaults
                extra = {}
                if speculate_k:
                    extra["speculate_k"] = speculate_k
                if system_prefix:
                    extra["system_prefix"] = system_prefix
                if kv_page_size:
                    # paged-KV serving: cache HBM scales with the page
                    # pool (live tokens), not max_slots x max_len
                    extra["kv_page_size"] = kv_page_size
                    extra["kv_pages"] = kv_pages
                    if paged_kernel is not None:
                        # explicit kernel-vs-gather override; absent =
                        # the ops-level auto rule (kernel on TPU only)
                        extra["paged_kernel"] = bool(paged_kernel)
                    if host_kv_pages:
                        # host-RAM page tier: the admission budget
                        # becomes HBM + host pages (serving/kv_tier.py)
                        extra["host_kv_pages"] = int(host_kv_pages)
                if draft_trial_id and speculate_k:
                    # draft-MODEL speculation: a second (smaller) trial
                    # drafts; its own knobs shape it (same tokenizer
                    # family enforced by the engine's vocab check)
                    d_dump = param_store.load(draft_trial_id)
                    if d_dump is None:
                        raise KeyError("no parameters for draft trial "
                                       f"{draft_trial_id!r}")
                    d_model = model_class(**(draft_knobs or knobs))
                    d_model.load_parameters(d_dump)
                    extra["draft_model"] = d_model
                self.engine = self.model.make_decode_engine(
                    max_slots=max_slots, max_new_tokens=max_new_tokens,
                    steps_per_sync=steps_per_sync, **extra)
            else:
                # the stack enables decode_loop for every LM-task model;
                # a template without an engine still serves fine through
                # the micro-batcher — degrade, don't die
                import logging

                logging.getLogger(__name__).warning(
                    "%s has no make_decode_engine; serving through the "
                    "predict() micro-batcher instead of the continuous-"
                    "batching decode loop", model_class.__name__)
        if self.role != "unified" and not getattr(
                self.engine, "supports_kv_ship", False):
            # fail the DEPLOY, not the serve thread: a role-configured
            # worker whose engine cannot extract/install KV shipments
            # would silently serve unified and defeat the placement
            raise ValueError(
                f"worker role {self.role!r} requires an engine with "
                "KV shipment support (supports_kv_ship); this "
                "deployment's engine has none")
        if self.engine is not None:
            # engine counters surface on /metrics under their BARE
            # names (kv_pages_used, admission_stalls, …) — the hub
            # publish below keeps the engine_ prefix for back-compat
            st = self.engine.stats
            if hasattr(st, "snapshot"):
                self.metrics.register_stats(st)
            else:  # duck-typed user engine with a plain dict
                self.metrics.register_stats(lambda: dict(st))
            if hasattr(self.engine, "span_sink"):
                # request-lifecycle events -> trace spans + histograms
                self.engine.span_sink = self._engine_span
            tier = getattr(getattr(self.engine, "engine", self.engine),
                           "tier", None)
            if tier is not None:
                # host-tier transfers feed the worker's latency
                # histogram (observed on the tier thread — the
                # registry's instruments are locked)
                tier.observe_transfer = self._h_kv_transfer.observe
        self._warmup()
        self._share_prefix_snapshot()

    def _admission_check(self, max_slots: int, n_extra_adapters: int,
                         draft=None, kv_page_size: int = 0,
                         kv_pages: int = 0,
                         host_kv_pages: int = 0) -> None:
        """Refuse a deployment whose serving footprint (params + KV
        cache + stacked adapters + draft params/cache + working set)
        exceeds the device's HBM, BEFORE any engine build/compile —
        the serving twin of the train worker's check. Templates opt in
        by exposing ``estimate_serving_device_bytes``; the limit
        resolution is shared (``worker.admission``). Micro-batch
        deployments (no decode loop) pass ``max_slots=0``: no engine
        means no KV cache to charge. A paged-KV deployment
        (``kv_page_size > 0``) is budgeted at its PAGE POOL, not
        max_slots × max_len — the admission headroom the block-table
        cache exists to create."""
        est = getattr(self.model, "estimate_serving_device_bytes", None)
        if est is None:
            return
        from .admission import resolve_device_limit

        limit = resolve_device_limit()
        if not limit:
            return
        try:
            kwargs = {"max_slots": max_slots,
                      "n_extra_adapters": n_extra_adapters}
            if draft is not None:
                kwargs["draft"] = draft
            if kv_page_size:  # only when set: estimators that predate
                # paged KV keep admitting their deployments
                kwargs["kv_page_size"] = kv_page_size
                kwargs["kv_pages"] = kv_pages
                if host_kv_pages:
                    # host tier: validated by the estimator (mirrors
                    # the engine rule) and reported as host RAM — it
                    # never counts toward the HBM total below
                    kwargs["host_kv_pages"] = host_kv_pages
            budget = est(**kwargs)
            total = int(budget["total"])
        except Exception as e:  # an estimator bug must never block an
            # admissible deployment — but it must be VISIBLE: silently
            # skipping here disables serving admission control
            # fleet-wide until workers start OOMing (ADVICE.md r5)
            import logging

            logging.getLogger(__name__).warning(
                "serving admission check skipped: "
                "estimate_serving_device_bytes raised %r", e,
                exc_info=True)
            return
        if total > limit:
            raise ValueError(
                "serving admission control: estimated "
                f"{total / 2**30:.2f}GiB footprint exceeds the "
                f"{limit / 2**30:.2f}GiB device limit (breakdown: "
                f"{ {k: round(v / 2**30, 3) for k, v in budget.items()} }"
                " GiB); lower max_slots/max_len or enable "
                "quantize_int8/kv_cache_int8")

    def _warmup(self) -> None:
        """Pre-compile the serving path at boot so the FIRST request
        doesn't pay XLA compilation (seconds to minutes on TPU)."""
        import logging

        try:
            if self.engine is not None:
                # one dummy token through the fused decode step
                self.engine.submit("__warmup__", "warmup", max_new=1)
                while self.engine.busy:
                    self.engine.step()
                self.engine.poll()  # drop the dummy completion
                # don't count the dummy in served-traffic metrics;
                # engines with capacity gauges (paged-KV pool size)
                # scrub counters only — duck-typed user engines without
                # reset_stats get the plain zeroing
                if hasattr(self.engine, "reset_stats"):
                    self.engine.reset_stats()
                else:
                    st = self.engine.stats
                    st.update({k: 0 for k in list(st)})
            else:
                self.model.warmup()
        except Exception:  # noqa: BLE001 — slower first request, not a
            logging.getLogger(__name__).warning(  # dead worker
                "serving warmup failed; first request pays the compile",
                exc_info=True)
            if self.engine is not None:
                # a failed step may have consumed the donated cache and
                # left the dummy occupying a slot: rebuild device state
                # so the loop doesn't admit real requests into a broken
                # engine
                self.engine.reset()

    def _share_prefix_snapshot(self) -> None:
        """Cross-worker prefix sharing (scale-out pools): a shared
        system prefix prefilled by ONE replica serves every replica of
        the job. The replica that found a peer's published blob at
        boot skipped its own prefix prefill entirely and installs the
        blob here; the first replica (no blob yet) publishes the
        snapshot it just computed. Both snapshots are bit-identical
        (same module/params/tokenizer) so which replica wins the
        publish race is immaterial; best-effort by design — any
        failure leaves a locally-computed snapshot serving."""
        if not self.pool_id or self.engine is None \
                or not self._system_prefix:
            return
        exp = getattr(self.engine, "export_prefix", None)
        imp = getattr(self.engine, "import_prefix", None)
        if exp is None or imp is None:
            return
        import logging

        key = f"prefix:{self.pool_id}:0"
        if self._peer_prefix_blob is not None:
            blob, self._peer_prefix_blob = self._peer_prefix_blob, None
            try:
                imp(blob)
                self.stats.inc("kv_imports_installed")
            except Exception:  # noqa: BLE001 — a bad/stale peer blob
                # must not leave the worker prefix-less: fall back to
                # computing the snapshot locally (what an unshared
                # boot would have done)
                logging.getLogger(__name__).warning(
                    "peer prefix snapshot rejected; registering the "
                    "prefix locally", exc_info=True)
                self.engine.register_prefix(self._system_prefix)
            return
        try:
            blob = exp()
            if blob is not None and self.hub.get_blob(key) is None:
                self.hub.put_blob(key, pack_message(blob))
        except Exception:  # noqa: BLE001 — publishing is a peer
            # optimization; this worker's own snapshot already serves
            logging.getLogger(__name__).warning(
                "prefix snapshot publish failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None
        close = getattr(self.engine, "close", None)
        if close is not None:
            # tiered engines own a transfer thread + pinned host pool;
            # micro-batch engines have no close and need none
            close()

    def drain(self) -> None:
        """Begin a graceful drain: stop admitting new requests (they
        get an immediate structured ``draining`` rejection the
        predictor fails over on), finish every in-flight request —
        including streams — then exit the serve loop cleanly (the
        process exits 0: a drained worker is a completed one, not a
        crash to respawn). Idempotent; safe from any thread (the obs
        sidecar's /drain handler and the queue control path both land
        here)."""
        if self._draining.is_set():
            return
        import logging

        logging.getLogger(__name__).info(
            "%s draining: finishing in-flight work, rejecting new",
            self.worker_id)
        self._draining.set()
        # publish immediately so the predictor's breaker board learns
        # of the drain from stats, not only from rejection replies
        self._publish_stats()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def serve_obs(self, host: str = "127.0.0.1",
                  port: int = 0) -> Tuple[str, int]:
        """Start the observability sidecar (``GET /metrics`` Prometheus
        text, ``GET /debug/requests?n=K`` trace records, ``POST
        /drain``) on a daemon thread; returns its (host, port). The
        serve loop never touches it — scrapes read the same locked
        registry the loop writes, and drain flips an Event the loop
        polls."""
        self._obs_server = ObsServer(self.metrics, self.traces,
                                     host=host, port=port)
        # the drain control endpoint (rolling restarts): mounted on the
        # sidecar because the worker itself is a queue consumer with no
        # HTTP surface of its own
        self._obs_server.http.route(
            "POST", "/drain",
            lambda _m, _b, _h: (self.drain() or
                                (200, {"ok": True, "draining": True})))
        host, port = self._obs_server.start()
        # GIL-atomic int store read by the serve loop's stats
        # publisher; a stale 0 only delays the obs_port advertisement
        # by one publication
        self._obs_port = port  # rafiki: noqa[shared-state-race]
        return host, port

    #: loop iterations between stats publications to the hub
    STATS_EVERY = 50
    #: how long published counters stay trustworthy: the loop publishes
    #: at least every STATS_EVERY x poll_timeout seconds (~25s at the
    #: defaults), so an uptime_s that has not advanced for this long
    #: means a dead/hung/partitioned worker, not a slow one
    STALE_AFTER_S = 60.0

    def _publish_stats(self) -> None:
        """Push this worker's counters to the hub so the predictor's
        /health can surface them (silent expiry drops are otherwise
        indistinguishable from gather timeouts on the predictor side).

        Snapshots are taken through the obs StatsMaps' own locks — the
        only race-free read while the engine thread mutates (iterating
        the live dict here used to be able to blow up with "dictionary
        changed size during iteration" under load)."""
        stats = self.stats.snapshot()
        stats["role"] = self.role  # disaggregated placement: the
        # router excludes prefill-role workers from serving selection
        # and targets them for the prefill leg
        stats["draining"] = self._draining.is_set()  # breaker-board
        # scatter exclusion during rolling restarts; the respawned
        # worker's fresh False is what re-admits the id
        stats["published_at"] = time.time()  # for humans; staleness
        # rides the MONOTONIC pair below — a wall-clock step (NTP, VM
        # migration) must neither grey out a healthy worker nor let a
        # dead one's counters pose as current
        stats["uptime_s"] = time.monotonic() - self._boot_mono
        stats["stale_after_s"] = self.STALE_AFTER_S
        if self._obs_port:
            stats["obs_port"] = self._obs_port  # where /metrics lives
        if self.engine is not None:
            snap = (self.engine.stats_snapshot()
                    if hasattr(self.engine, "stats_snapshot")
                    else dict(self.engine.stats))
            stats.update({f"engine_{k}": v for k, v in snap.items()})
            # bucket-derived latency summaries (dashboard TTFT/e2e)
            stats["ttft_p50_s"] = self._h_ttft.quantile(0.50)
            stats["ttft_p95_s"] = self._h_ttft.quantile(0.95)
            stats["e2e_p50_s"] = self._h_e2e.quantile(0.50)
            stats["e2e_p95_s"] = self._h_e2e.quantile(0.95)
            # queue-wait p95: the router's cleanest "this worker is
            # behind" signal (TTFT includes prefill length, queue wait
            # is pure backlog)
            stats["queue_p95_s"] = self._h_queue.quantile(0.95)
            # per-class latency gauges: the predictor's brownout
            # ladder steps on slo_interactive_ttft_p95_s; the rest
            # make the SLO tradeoff visible per class on /health.
            # WINDOWED (recent 256 samples), not the cumulative
            # histogram quantile — the ladder must de-escalate when
            # the overload actually ends, not hours later
            with self._slo_win_lock:
                for c in SLO_CLASSES:
                    stats[f"slo_{c}_ttft_p95_s"] = _window_p95(
                        self._slo_ttft_win[c])
                    stats[f"slo_{c}_e2e_p95_s"] = _window_p95(
                        self._slo_e2e_win[c])
        try:
            self.hub.put_worker_stats(self.worker_id, stats)
        except Exception:  # rafiki: noqa[silent-except] —
            pass           # observability must never kill the loop

    def _engine_span(self, event: str, rid: Any, attrs: dict) -> None:
        """Decode-engine lifecycle hook: admitted / prefill /
        first_token / decode_mark / done events become trace spans, and
        the queued→X durations feed the latency histograms. Runs on the
        serve-loop thread (the engine's step caller), so the rid→trace
        map needs no lock; unknown rids (the warmup dummy) are
        ignored."""
        entry = self._req_obs.get(rid)
        if entry is None:
            return
        tid, t_queued, slo = entry
        now = time.monotonic()
        if event == "admitted":
            if not attrs.get("resumed"):
                # a preempt-resume RE-admission is not queue wait: the
                # gap since submit includes the victim's own
                # pre-preemption generation time, and queue_p95_s is
                # the router's least-loaded input — a worker doing
                # preemptions (correctly protecting interactive) must
                # not read as backlogged for it
                self._h_queue.observe(now - t_queued)
            self.traces.add_span(tid, "admitted", worker=self.worker_id,
                                 **attrs)
        elif event == "first_token":
            self._h_ttft.observe(now - t_queued)
            h = self._h_ttft_slo.get(slo)
            if h is not None:
                h.observe(now - t_queued)
                with self._slo_win_lock:
                    self._slo_ttft_win[slo].append((now,
                                                    now - t_queued))
            self.traces.add_span(tid, "first_token")
        elif event == "done":
            dt = now - t_queued
            self._h_e2e.observe(dt)
            h = self._h_e2e_slo.get(slo)
            if h is not None:
                h.observe(dt)
                with self._slo_win_lock:
                    self._slo_e2e_win[slo].append((now, dt))
            tokens = attrs.get("tokens") or 0
            if tokens and dt > 0:
                self._h_tps.observe(tokens / dt)
            self.traces.add_span(tid, "done", **attrs)
            self._req_obs.pop(rid, None)
        else:
            # incl. `preempted`: the span joins the timeline but the
            # rid entry stays — the victim resumes under the same id
            self.traces.add_span(tid, event, **attrs)

    def _count_dropped(self, n: int) -> None:
        if n <= 0:
            return
        import logging

        total = self.stats.inc("dropped_expired", n)
        # log the first drop and then every 100th: one line is enough to
        # diagnose skew, a line per query would flood under overload
        if total == n or total % 100 < n:
            logging.getLogger(__name__).warning(
                "%s dropped %d expired quer%s (%d total) — if the "
                "predictor only reports timeouts, check clock skew "
                "between predictor and worker hosts",
                self.worker_id, n, "y" if n == 1 else "ies", total)

    def _reject_expired(self, m: dict) -> None:
        """Answer a past-deadline query with a structured ``expired``
        rejection instead of a silent drop: the predictor records a
        skipped vote (unary gather) or triggers stream failover
        IMMEDIATELY, instead of burning the remaining gather budget
        waiting on silence. The drop counter and its diagnostic log
        line stay — `dropped_expired` growing alongside `expired`
        replies is still the clock-skew tell (ADVICE r3)."""
        self._count_dropped(1)
        if "id" not in m:
            return
        tid = str(m.get("trace_id") or "")
        if tid:  # the drop is visible in the trace, not just a
            # counter — joins the predictor's record
            self.traces.start(tid, request_id=str(m.get("id") or ""),
                              span="expired", worker=self.worker_id)
        self.hub.push_prediction(m["id"], pack_message(
            {"id": m["id"], "worker_id": self.worker_id,
             "predictions": [], "expired": True,
             "error": "query expired in transit "
                      "(deadline exceeded before pop)"}))

    def _handle_control(self, m: dict) -> None:
        """Control messages ride the ordinary query queue (``{"control":
        "drain"}``): the queue is the one channel every deployment
        shape shares, HTTP sidecar or not."""
        cmd = str(m.get("control") or "")
        if cmd == "drain":
            self.drain()
        else:
            import logging

            logging.getLogger(__name__).warning(
                "%s ignoring unknown control message %r",
                self.worker_id, cmd)

    def _reject_draining(self, m: dict) -> None:
        """Answer a message popped while draining with an immediate
        structured rejection: the predictor fails the request over to a
        healthy replica instead of timing out on a queue nobody will
        serve."""
        if "id" not in m:
            return
        self.stats.inc("drain_rejected")
        tid = str(m.get("trace_id") or "")
        if tid:
            self.traces.start(tid, request_id=str(m.get("id") or ""),
                              span="drain_rejected",
                              worker=self.worker_id)
        self.hub.push_prediction(m["id"], pack_message(
            {"id": m["id"], "worker_id": self.worker_id,
             "predictions": [], "error": "worker draining",
             "draining": True}))

    def _drain_reject_queued(self) -> None:
        """Flush the query queue with drain rejections (non-blocking)."""
        raw = self.hub.pop_query(self.worker_id, 0.0)
        while raw is not None:
            m = unpack_message(raw)
            if not m.get("control"):
                self._reject_draining(m)
            raw = self.hub.pop_query(self.worker_id, 0.0)

    # ---- data-plane outage handling ----
    #: ceiling on the pause between hub retries while the data plane
    #: is down — long enough not to spin, short enough that the worker
    #: notices the respawned kvd within a beat of its WAL replay
    HUB_OUTAGE_PAUSE_S = 0.5

    def _hub_outage_pause(self, err: Exception,
                          poll_timeout: float) -> None:
        """The kvd is unreachable past the client's reconnect window:
        PAUSE the serve loop instead of crashing into a respawn storm.
        The obs sidecar keeps answering /metrics and /health the whole
        time (it never touches the hub), `data_plane_down` flips to 1,
        and in-flight engine state stays seated — when the supervisor's
        respawn-with-replay brings the kvd back, the next loop tick
        picks up exactly where it paused."""
        import logging

        if not self._dp_down:
            self._dp_down = True
            self.stats.set("data_plane_down", 1)
            self.stats.inc("hub_outages")
            logging.getLogger(__name__).warning(
                "%s: data plane unreachable (%s) — serve loop paused "
                "(health stays up; retrying every %.1fs)",
                self.worker_id, err,
                min(self.HUB_OUTAGE_PAUSE_S, max(poll_timeout, 0.05)))
        self._stop.wait(min(self.HUB_OUTAGE_PAUSE_S,
                            max(poll_timeout, 0.05)))

    def _hub_ok(self) -> None:
        """A hub op reached the kvd again: clear the outage flag."""
        if self._dp_down:
            import logging

            self._dp_down = False
            self.stats.set("data_plane_down", 0)
            logging.getLogger(__name__).warning(
                "%s: data plane reachable again — serve loop resumed",
                self.worker_id)
            self._publish_stats()  # fresh liveness beats the stale
            #                        pre-outage publish immediately

    # ---- the loop ----
    def run(self, poll_timeout: float = 0.5,
            max_iterations: Optional[int] = None) -> None:
        if self.role == "prefill":
            # prefill is throughput work; decode is latency work. On a
            # co-located host the prompt chew must never preempt a
            # decode loop's step, so the prefill serve thread runs
            # niced (Linux niceness is per-thread; pid 0 = this
            # thread). Best-effort — a host that refuses leaves both
            # threads at default priority.
            try:
                os.setpriority(os.PRIO_PROCESS, 0, 10)
            except (AttributeError, OSError):
                pass
        if self.engine is not None:
            return self._run_decode_loop(poll_timeout, max_iterations)
        n = 0
        while not self._stop.is_set():
            if max_iterations is not None and n >= max_iterations:
                break
            n += 1
            if n % self.STATS_EVERY == 1:  # incl. first iteration:
                self._publish_stats()      # fresh boots appear at once
            try:
                if self._draining.is_set():
                    # micro-batch serving has no in-flight state
                    # between iterations: reject what is queued, leave
                    self._drain_reject_queued()
                    break
                first = self.hub.pop_query(self.worker_id, poll_timeout)
                self._hub_ok()
                if first is None:
                    continue
                messages = [unpack_message(first)]
                while len(messages) < self.max_batch_msgs:
                    more = self.hub.pop_query(self.worker_id, 0.0)
                    if more is None:
                        break
                    messages.append(unpack_message(more))
                serve = []
                for m in messages:
                    if m.get("control"):
                        self._handle_control(m)
                    else:
                        serve.append(m)
                live = []
                for m in serve:
                    if _expired(m, skew_est=self._skew):
                        self._reject_expired(m)
                    else:
                        live.append(m)
                if live:
                    # messages popped alongside a drain control
                    # preceded the drain: they are in-flight and served
                    self._serve_batch(live)
            except ConnectionError as e:
                # data plane unreachable past the reconnect window:
                # pause and retry — health stays up on the obs sidecar
                self._hub_outage_pause(e, poll_timeout)
        self._publish_stats()  # final counters visible after stop

    def _run_decode_loop(self, poll_timeout: float,
                         max_iterations: Optional[int]) -> None:
        """Continuous batching: admit queued messages into engine slots
        between steps; reply per message once all its queries finish.

        One loop iteration = (drain the queue, admit, one engine step,
        harvest). While the engine is busy the queue pop is non-blocking
        so decoding never stalls on an empty queue.

        Data-plane outages (a hub op exhausting its reconnect window)
        PAUSE the loop here — in-flight engine state, the inflight
        table, and streaming ids all survive the pause, so when the
        supervisor's respawn-with-replay brings the kvd back the loop
        resumes decoding the same streams; a delta pushed into the
        dead window is healed by the final predictions message (the
        client's replace/tail contract)."""
        # message id -> [n_pending, {query_index: text}]
        inflight: dict = {}
        streaming: set = set()  # message ids that asked for token deltas
        state = {"n": 0}
        while not self._stop.is_set():
            try:
                self._decode_serve(inflight, streaming, state,
                                   poll_timeout, max_iterations)
                break  # served to completion (stop/drain/iterations)
            except ConnectionError as e:
                self._hub_outage_pause(e, poll_timeout)
        if self.chaos_killed:
            return  # injected sudden death: no final publish either
        self._publish_stats()  # final counters visible after stop

    def _decode_serve(self, inflight: dict, streaming: set,
                      state: dict, poll_timeout: float,
                      max_iterations: Optional[int]) -> None:
        while not self._stop.is_set():
            n = state["n"]
            if max_iterations is not None and n >= max_iterations:
                break
            n = state["n"] = n + 1
            if n % self.STATS_EVERY == 1:  # incl. first iteration
                self._publish_stats()
            # held shipped-KV requests count as busy: the loop must
            # keep pumping the shipment queue instead of parking on an
            # empty query queue while a blob is in flight
            busy = self.engine.busy or bool(self._pending_kv)
            raw = self.hub.pop_query(self.worker_id,
                                     0.0 if busy else poll_timeout)
            self._hub_ok()
            while raw is not None:
                m = unpack_message(raw)
                if m.get("control"):
                    self._handle_control(m)
                    raw = self.hub.pop_query(self.worker_id, 0.0)
                    continue
                if self._draining.is_set():
                    # draining: in-flight requests keep decoding below,
                    # new arrivals get an immediate structured
                    # rejection the predictor fails over on
                    self._reject_draining(m)
                    raw = self.hub.pop_query(self.worker_id, 0.0)
                    continue
                if _expired(m, skew_est=self._skew):
                    self._reject_expired(m)
                    raw = self.hub.pop_query(self.worker_id, 0.0)
                    continue
                if m.get("prefill_for"):
                    # the PREFILL leg of a disaggregated stream: chew
                    # the prompt, ship the KV pages to the decode
                    # worker named in the payload. Never replied to —
                    # the decode leg's local re-prefill covers every
                    # failure mode here
                    self._handle_prefill_leg(m)
                elif m.get("kv_from") and self._can_import_kv():
                    # the DECODE leg: a prefill worker is computing
                    # this prompt's KV — hold admission for up to
                    # kv_wait_s so the shipment can skip our prefill
                    mid = m["id"]
                    self._kv_seen_traffic = True
                    self._pending_kv[mid] = [
                        m, time.monotonic() + self.kv_wait_s, {},
                        time.monotonic()]
                else:
                    if m.get("kv_from"):
                        # can't hold for the shipment (kv_wait_s=0 or
                        # no shipment-capable engine) but a prefill
                        # worker WILL push blobs for this request: the
                        # pump must keep draining the shipment queue
                        # (dropping unmatched blobs) or the multi-MB
                        # pushes accumulate unboundedly
                        self._kv_seen_traffic = True
                    self._admit_decode_message(m, inflight, streaming)
                raw = self.hub.pop_query(self.worker_id, 0.0)
            self._pump_kv_shipments(inflight, streaming)
            stepped = self.engine.busy
            if stepped:
                try:
                    t_step = time.monotonic()
                    n_live = self.engine.step()
                    self._h_step.observe(time.monotonic() - t_step)
                    self._h_occupancy.observe(n_live)
                except Exception:
                    err = traceback.format_exc()
                    for mid in list(inflight):
                        self.hub.push_prediction(mid, pack_message(
                            {"id": mid, "worker_id": self.worker_id,
                             "predictions": [], "error": err}))
                        del inflight[mid]
                    streaming.clear()
                    # every in-flight request's timeline ends HERE, not
                    # in silence: the reset below preempts all occupants
                    for _rid, (tid, _t, _slo) in list(
                            self._req_obs.items()):
                        self.traces.add_span(tid, "preempted",
                                             error="engine step failed")
                    self._req_obs.clear()
                    # a failed step may have consumed the donated cache:
                    # drop every occupant and rebuild device state, or
                    # the loop hot-spins on a permanently broken engine
                    self.engine.reset()
                    continue
                if self.chaos is not None and self.chaos.should_kill(
                        int(self.engine.stats.get("tokens_generated",
                                                  0) or 0)):
                    # injected sudden death: exit WITHOUT replying,
                    # streaming, or publishing — exactly what a killed
                    # process looks like to the rest of the stack (the
                    # fused step that crossed the threshold never gets
                    # its tokens out)
                    import logging

                    logging.getLogger(__name__).warning(
                        "%s chaos-killed after %s generated tokens",
                        self.worker_id,
                        self.chaos.cfg.kill_after_tokens)
                    self.chaos_killed = True
                    return
                if streaming and hasattr(self.engine, "poll_partial"):
                    # per-message delta events between steps: the reply
                    # queue carries them ahead of the final predictions
                    # message (pushes are FIFO per query id)
                    deltas: dict = {}
                    for (mid, qi), delta in self.engine.poll_partial():
                        if mid in streaming:
                            deltas.setdefault(mid, {})[str(qi)] = delta
                    for mid, d in deltas.items():
                        self.hub.push_prediction(mid, pack_message(
                            {"id": mid, "worker_id": self.worker_id,
                             "delta": d}))
            # harvest runs even when the engine is idle: a resume whose
            # forced prefix covered the whole token budget completes
            # without ever occupying a slot (TextDecodeEngine's
            # instant-done path)
            for (mid, qi), text in self.engine.poll():
                entry = inflight.get(mid)
                if entry is None:
                    continue
                entry[1][qi] = text
                if len(entry[1]) >= entry[0]:
                    preds = [entry[1].get(i) for i in range(entry[0])]
                    self.hub.push_prediction(mid, pack_message(
                        {"id": mid, "worker_id": self.worker_id,
                         "predictions": preds}))
                    for i in range(entry[0]):  # instant-done requests
                        # emit no engine `done` span to clear these
                        self._req_obs.pop((mid, i), None)
                    del inflight[mid]
                    streaming.discard(mid)
            self._ship_finished_prefill()
            if self._draining.is_set() and not inflight \
                    and not self._pending_kv and not self.engine.busy:
                break  # drain complete: every in-flight stream answered

    # ---- disaggregated prefill/decode (see serving/kv_transfer.py) --
    def _can_import_kv(self) -> bool:
        """May this worker hold a request for a KV shipment? Any
        shipment-capable engine qualifies (a unified worker benefits
        the same way when the router chose to disaggregate); a
        zero wait window disables holding entirely."""
        return (self.kv_wait_s > 0
                and getattr(self.engine, "supports_kv_ship", False))

    def _handle_prefill_leg(self, m: dict) -> None:
        """Run a disaggregated request's PREFILL leg: submit each query
        prefill-only and remember where the finished KV blobs ship
        (:meth:`_ship_finished_prefill`). Fire-and-forget by contract —
        on ANY local failure the decode worker's wait window expires
        and it re-prefills locally (token-exact), so this path only
        logs, never replies."""
        import logging

        ship_to = str(m.get("prefill_for") or "")
        sub = getattr(self.engine, "submit_prefill", None)
        if not ship_to or sub is None or self._draining.is_set() \
                or _expired(m, skew_est=self._skew):
            return
        qs = m.get("queries")
        qs = list(qs) if not isinstance(qs, (list, tuple)) else qs
        samp = _safe_sampling(m.get("sampling"))
        tid = str(m.get("trace_id") or "") or mint_trace_id()
        try:
            slo = normalize_slo(m.get("slo"), default=self.default_slo)
        except ValueError:
            slo = self.default_slo
        kwargs = {"slo": slo}
        if samp.get("adapter_id"):
            # the KV is a function of the adapter that computes it —
            # the decode side validates the blob against the request's
            kwargs["adapter_id"] = samp["adapter_id"]
        self.traces.start(tid, request_id=str(m.get("id") or ""),
                          span="prefill_leg", worker=self.worker_id,
                          ship_to=ship_to, n_queries=len(qs))
        try:
            for qi, text in enumerate(qs):
                sub((m["id"], qi), str(text), **kwargs)
        except ValueError as e:
            logging.getLogger(__name__).warning(
                "%s prefill leg rejected (%s); decode worker will "
                "re-prefill locally", self.worker_id, e)
            return
        self._kv_outbox[m["id"]] = [
            ship_to, tid, len(qs),
            time.monotonic() + _KV_OUTBOX_TTL_S]

    def _ship_finished_prefill(self) -> None:
        """Forward completed prefill-only KV blobs to their decode
        workers. Costs one no-op call on workers with no prefill
        traffic (the engine's done list is empty)."""
        poll = getattr(self.engine, "poll_kv", None)
        if poll is None:
            return
        for (mid, qi), blob in poll():
            entry = self._kv_outbox.get(mid)
            if entry is None:
                continue
            ship_to, tid = entry[0], entry[1]
            entry[2] -= 1  # shipped OR failed, this query is settled
            if entry[2] <= 0:
                del self._kv_outbox[mid]
            try:
                self.hub.push_kv(ship_to, pack_message(
                    {"id": mid, "qi": int(qi), "blob": blob,
                     "from": self.worker_id}))
                self.stats.inc("kv_ships_sent")
                self.traces.add_span(tid, "kv_shipped", qi=int(qi),
                                     nbytes=int(blob.get("nbytes", 0)
                                                or 0))
            except Exception:  # noqa: BLE001 — a failed shipment is
                # the decode side's local re-prefill, not our crash
                import logging

                logging.getLogger(__name__).warning(
                    "%s KV shipment to %s failed", self.worker_id,
                    ship_to, exc_info=True)
        if self._kv_outbox:
            # legs whose slots will never produce a blob (engine
            # reset, preemption of a prefill-only slot) must not
            # accumulate forever; the decode side's wait window
            # expired into a local re-prefill long ago
            now = time.monotonic()
            for mid in [k for k, e in self._kv_outbox.items()
                        if now > e[3]]:
                del self._kv_outbox[mid]

    def _kv_stage_budget_ok(self) -> bool:
        """Eagerly device-stage an arriving KV blob only when it will
        install soon. With the engine's admission queue backed up, a
        staged blob sits device-RESIDENT for its whole wait — a burst
        of disaggregated arrivals on a saturated decode worker would
        pin queue-depth × blob-size HBM the unified path never pays.
        Unstaged blobs install from their host bytes at seat time:
        exactly as correct, just without the upload/step overlap."""
        if len(self._pending_kv) > 4:
            return False
        st = self.engine.stats
        return not any(st.get(f"queued_{c}", 0)
                       for c in ("interactive", "batch", "background"))

    def _pump_kv_shipments(self, inflight: dict, streaming: set) -> None:
        """Decode-leg intake: drain arrived KV shipments into held
        requests, admit every request whose blobs are complete, and
        expire wait windows into local re-prefills. Runs once per loop
        iteration, non-blocking; free when nothing is pending."""
        if not self._pending_kv and not self._kv_seen_traffic:
            return
        now = time.monotonic()
        raw = self.hub.pop_kv(self.worker_id, 0.0)
        while raw is not None:
            try:
                ship = unpack_message(raw)
                mid, qi = ship["id"], int(ship["qi"])
                blob = ship["blob"]
            except Exception:  # noqa: BLE001 — a torn shipment is a
                # degradation (local re-prefill), never a serve-thread
                # crash
                import logging

                logging.getLogger(__name__).warning(
                    "%s discarding undecodable KV shipment",
                    self.worker_id, exc_info=True)
                blob = None
                mid = qi = None
            if mid is not None and mid in self._pending_kv \
                    and blob is not None:
                stage = getattr(self.engine, "stage_kv_blob", None)
                if stage is not None and self._kv_stage_budget_ok():
                    try:
                        # device staging starts NOW, overlapping the
                        # in-flight step: admission installs a blob
                        # whose h2d copies already ran
                        blob = stage(blob)
                    except Exception:  # rafiki: noqa[silent-except] —
                        pass           # staging is an optimization
                self._pending_kv[mid][2][qi] = blob
            raw = self.hub.pop_kv(self.worker_id, 0.0)
        for mid in list(self._pending_kv):
            m, deadline, blobs, t_queued = self._pending_kv[mid]
            qs = m.get("queries")
            n = len(qs) if isinstance(qs, (list, tuple)) else 1
            if len(blobs) >= n:
                del self._pending_kv[mid]
                self._admit_decode_message(m, inflight, streaming,
                                           kv_blobs=blobs,
                                           t_queued=t_queued)
            elif now >= deadline or self._draining.is_set():
                # shipment late/lost (or we are draining and must not
                # wait): degrade to a local re-prefill — token-exact,
                # the stream just pays the prefill it hoped to skip
                del self._pending_kv[mid]
                self.stats.inc("kv_wait_timeouts")
                self._admit_decode_message(m, inflight, streaming,
                                           t_queued=t_queued)
        if self._pending_kv and not self.engine.busy:
            # nothing to decode while the blob is in flight: yield the
            # CPU briefly instead of hot-spinning the loop, but stay
            # far under shipment latency so installs are prompt
            time.sleep(0.002)

    def _admit_decode_message(self, m: dict, inflight: dict,
                              streaming: set,
                              kv_blobs: Optional[Dict[int, Any]] = None,
                              t_queued: Optional[float] = None) -> None:
        """Admit one popped message into the engine (the decode loop's
        submission path, shared by immediate admission and the
        deferred shipped-KV path). ``kv_blobs``: per-query-index KV
        shipments to install instead of prefilling; a blob the engine
        rejects degrades that query to a local re-prefill."""
        qs = m["queries"]
        qs = list(qs) if not isinstance(qs, (list, tuple)) else qs
        if not qs:  # answer empty messages immediately, like
            # _serve_batch does — nothing will ever poll() for them
            self.hub.push_prediction(m["id"], pack_message(
                {"id": m["id"], "worker_id": self.worker_id,
                 "predictions": []}))
            return
        tid = str(m.get("trace_id") or "") or mint_trace_id()
        if t_queued is None:
            t_queued = time.monotonic()
        self.traces.start(tid, request_id=str(m["id"]),
                          span="queued",
                          worker=self.worker_id,
                          n_queries=len(qs))
        samp = _safe_sampling(m.get("sampling"))
        # admission class: per-request override riding the
        # payload, else the job default. Defensive like
        # _safe_sampling: the predictor validates, but a
        # malformed value must degrade to the default,
        # never raise inside the serve loop
        try:
            slo = normalize_slo(m.get("slo"),
                                default=self.default_slo)
        except ValueError:
            slo = self.default_slo
        if "max_new" in samp:
            # per-request generation length, clamped by the
            # worker's configured cap: a client must not be
            # able to occupy a slot for longer than the
            # operator budgeted. getattr: duck-typed user
            # engines without a cap must not let a client
            # field kill the serve thread
            samp["max_new"] = min(
                samp["max_new"],
                getattr(self.engine, "max_new",
                        samp["max_new"]))
        fp = m.get("forced_prefix")
        fp = fp if isinstance(fp, dict) else {}
        if fp:
            self.traces.add_span(
                tid, "resumed",
                prefix_chars=sum(len(str(v))
                                 for v in fp.values()))
        try:
            if fp and not getattr(self.engine,
                                  "supports_resume",
                                  False):
                # checked BEFORE any submit (a per-query
                # check would leak the message's earlier
                # queries into the engine when a later one
                # rejects) — and structured, never a
                # TypeError that kills the thread
                raise ValueError(
                    "engine does not support stream "
                    "resume (forced_prefix)")
            for qi, text in enumerate(qs):
                kwargs = dict(samp)
                prefix = str(fp.get(str(qi), "") or "")
                if prefix:
                    kwargs["forced_prefix"] = prefix
                if getattr(self.engine, "supports_slo",
                           False):
                    # capability-gated like forced_prefix:
                    # a duck-typed user engine without the
                    # kwarg serves classless FIFO instead
                    # of dying on a TypeError
                    kwargs["slo"] = slo
                # _engine_span mutates this map too, but it is the
                # engine's span_sink callback and runs on this same
                # serve-loop thread — the model can't resolve callback
                # registration, so it sees a second context
                self._req_obs[(m["id"], qi)] = (  # rafiki: noqa[shared-state-race]
                    tid, t_queued, slo)
                blob = None if kv_blobs is None else kv_blobs.get(qi)
                if blob is not None and not prefix:
                    try:
                        self.engine.submit((m["id"], qi), str(text),
                                           kv_blob=blob, **kwargs)
                        self.stats.inc("kv_imports_installed")
                        self.traces.add_span(tid, "kv_installed",
                                             qi=qi)
                        continue
                    except ValueError:
                        # mismatched/corrupt shipment: degrade THIS
                        # query to a local re-prefill; a genuine
                        # submit error re-raises below and rejects
                        # the message as before
                        self.stats.inc("kv_import_fallbacks")
                self.engine.submit((m["id"], qi), str(text),
                                   **kwargs)
        except ValueError as e:
            # e.g. adapter_id out of range on a multi-
            # adapter engine: reject the whole message —
            # serving a different fine-tune than requested
            # would be a correct-looking wrong answer
            for qi in range(len(qs)):
                self._req_obs.pop((m["id"], qi), None)
            self.traces.add_span(tid, "rejected",
                                 error=str(e))
            self.hub.push_prediction(m["id"], pack_message(
                {"id": m["id"],
                 "worker_id": self.worker_id,
                 "predictions": [], "error": str(e)}))
        else:
            inflight[m["id"]] = [len(qs), {}]
            if m.get("stream"):
                streaming.add(m["id"])

    def _serve_batch(self, messages: List[dict]) -> None:
        # flatten all messages' queries into one forward pass
        t0 = time.monotonic()
        counts = []
        flat: List[Any] = []
        for m in messages:
            qs = m["queries"]
            qs = list(qs) if not isinstance(qs, (list, tuple)) else qs
            counts.append(len(qs))
            flat.extend(qs)
            tid = str(m.get("trace_id") or "")
            if tid:  # join the predictor's trace (micro-batch path has
                # no slot lifecycle — one queued + one served span)
                self.traces.start(tid, request_id=str(m.get("id") or ""),
                                  span="queued", worker=self.worker_id,
                                  n_queries=len(qs))
        try:
            preds = self.model.predict(flat)
            err = ""
        except Exception:
            preds = []
            err = traceback.format_exc()
        # split results back per message and reply on per-query-id queues
        ofs = 0
        dt = time.monotonic() - t0
        for m, c in zip(messages, counts):
            chunk = preds[ofs:ofs + c] if not err else []
            ofs += c
            reply = {"id": m["id"], "worker_id": self.worker_id,
                     "predictions": _to_plain(chunk)}
            if err:
                reply["error"] = err
            self.hub.push_prediction(m["id"], pack_message(reply))
            self._h_e2e.observe(dt)
            tid = str(m.get("trace_id") or "")
            if tid:
                self.traces.add_span(
                    tid, "error" if err else "served",
                    latency_s=round(dt, 4))


#: published-p95 samples older than this stop counting: an idle class
#: must read as recovered (empty window → 0.0 → ladder cooling), not
#: as its last overload forever
SLO_WINDOW_MAX_AGE_S = 60.0


def _window_p95(samples: "collections.deque",
                max_age_s: float = SLO_WINDOW_MAX_AGE_S) -> float:
    """Nearest-rank p95 over a bounded recent-(timestamp, value)
    window, pruning entries older than ``max_age_s`` first (append
    order is time order, so the prune is a popleft loop). Same
    quantile rule as the predictor's `nearest_rank`, kept local so
    the worker doesn't import the predictor module. Empty window →
    0.0, which the brownout ladder reads as cooling."""
    cutoff = time.monotonic() - max_age_s
    while samples and samples[0][0] < cutoff:
        samples.popleft()
    if not samples:
        return 0.0
    vals = sorted(v for _t, v in samples)
    n = len(vals)
    return vals[max(0, min(n - 1, math.ceil(0.95 * n) - 1))]


def _require_dict_or_none(value: Any, name: str) -> Optional[dict]:
    """Config values that must be a JSON object when present: silently
    coercing a malformed one would hide an operator mistake until an
    opaque shape error at first dispatch."""
    if value is None:
        return None
    if not isinstance(value, dict):
        raise ValueError(f"{name} must be a JSON object, got "
                         f"{type(value).__name__}")
    return value


def _safe_sampling(samp: Any) -> dict:
    """Client-supplied sampling params, coerced defensively: a malformed
    value (e.g. {"temperature": "hot"}) must degrade that request to the
    nearest valid config — never raise inside the decode loop, where an
    escaped exception kills the worker thread and every later request
    times out (one bad request = persistent denial of service)."""
    if not isinstance(samp, dict):
        samp = {}

    import math

    def num(key: str, cast, default):
        try:
            v = cast(samp.get(key, default))
        except (TypeError, ValueError, OverflowError):
            # OverflowError: int(float("inf")) — inf is legal msgpack,
            # and an escaped exception here kills the serve thread
            return default
        # NaN/inf would split behavior between the host's greedy-vs-
        # sampling program gate (NaN > 0 is False) and the device's
        # where(temp <= 0) select (also False) — same request, different
        # path depending on batch mix. Finite or default.
        return v if math.isfinite(v) else default

    out = {"temperature": num("temperature", float, 0.0),
           "top_k": num("top_k", int, 0),
           "top_p": num("top_p", float, 1.0),
           "seed": num("seed", int, 0)}
    eos = num("eos_id", int, None)  # absent/malformed → None
    if eos is not None and eos >= 0:
        out["eos_id"] = eos
    aid = num("adapter_id", int, 0)  # multi-adapter engines: which
    if aid:  # forward any non-default id, INCLUDING negatives — the
        # engine rejects out-of-range values and the caller gets an
        # error reply; silently mapping -1 to adapter 0 would be the
        # correct-looking wrong-tenant answer the validation exists for
        out["adapter_id"] = aid
    mn = num("max_new", int, 0)  # per-request generation length; the
    if mn and mn > 0:            # worker clamps to its configured cap
        out["max_new"] = mn      # (capacity protection) at submit time
    return out


def _expired(msg: dict, skew_s: float = EXPIRY_SKEW_TOLERANCE_S,
             skew_est: Optional[ClockSkewEstimator] = None) -> bool:
    """The predictor stamps each query with its gather deadline; a
    worker that pops it too late must drop it — the answer would land
    in a discarded reply queue and leak there forever (and the forward
    pass would be wasted compute).

    **Preferred path** (payloads carrying the relative ``ttl_s`` +
    ``sent_ts`` pair and a ``skew_est``): elapsed-since-scatter comes
    from the :class:`ClockSkewEstimator` — cross-host wall-clock skew
    cancels, so the pad shrinks from ``EXPIRY_SKEW_TOLERANCE_S`` to
    ``TTL_EXPIRY_PAD_S`` and a worker clock running minutes ahead no
    longer silently drops every fresh query.

    **Fallback** (old payloads / no estimator): the wall-clock
    ``deadline_ts`` judged on this host's clock, padded by ``skew_s``
    because deadline_ts is the PREDICTOR's wall clock (ADVICE r3):
    without the margin, cross-machine clock skew beyond the gather
    timeout makes a worker silently drop every query while the
    predictor only sees timeouts. The cost is at most one wasted
    forward per truly-late query; reply-queue TTLs are padded against
    the same constant."""
    import time

    ttl = msg.get("ttl_s")
    sent = msg.get("sent_ts")
    if (skew_est is not None and ttl is not None and sent is not None
            and isinstance(ttl, (int, float))
            and isinstance(sent, (int, float))):
        return skew_est.elapsed_since(float(sent)) \
            > float(ttl) + TTL_EXPIRY_PAD_S
    ts = msg.get("deadline_ts")
    return ts is not None and time.time() > float(ts) + skew_s  # rafiki: noqa[taint-wall-clock-flow] — the documented wall-clock FALLBACK (old payloads); ttl_s+skew_est above is the sanctioned path


def _tristate(v: Any) -> Optional[bool]:
    """Config value → the ``paged_kernel`` tri-state: absent /
    blank / ``"auto"`` mean None (the ops-level backend rule
    decides); anything else coerces to a hard bool override. One
    parse for the worker config AND the admin ``PAGED_KERNEL``
    budget key — two diverging coercions of the same value would be
    a config-dependent dispatch bug."""
    if v is None:
        return None
    if isinstance(v, str):
        s = v.strip().lower()
        if s in ("", "auto"):
            return None
        return s in ("1", "true", "on", "yes")
    return bool(v)


def _to_plain(preds: List[Any]) -> List[Any]:
    """Predictions as a list of plain lists/scalars (msgpack-safe)."""
    out = []
    for p in preds:
        if isinstance(p, np.ndarray):
            out.append(p.tolist())
        elif hasattr(p, "tolist"):
            out.append(np.asarray(p).tolist())
        else:
            out.append(p)
    return out


def main(argv: Optional[list] = None) -> int:
    """Service entrypoint: ``python -m rafiki_tpu.worker.inference``."""
    import argparse
    import json

    from ..parallel.multihost import initialize_from_env
    from ..utils.platform import apply_platform_env

    apply_platform_env()  # before any jax backend initializes
    initialize_from_env()  # multi-host rendezvous (no-op if unconfigured)

    from ..model.base import load_model_class
    from ..serving.queues import KVQueueHub

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True,
                        help="JSON: {model_file, model_class, trial_id, "
                             "knobs, param_store_uri, kv_host, kv_port, "
                             "worker_id}")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    with open(cfg["model_file"], "rb") as f:
        model_class = load_model_class(f.read(), cfg["model_class"])
    worker = InferenceWorker(
        model_class=model_class, trial_id=cfg["trial_id"],
        knobs=cfg.get("knobs", {}),
        param_store=ParamStore.from_uri(cfg["param_store_uri"]),
        hub=KVQueueHub(cfg["kv_host"], int(cfg["kv_port"])),
        worker_id=cfg["worker_id"],
        decode_loop=bool(cfg.get("decode_loop")),
        max_slots=int(cfg.get("max_slots", 8)),
        steps_per_sync=int(cfg.get("steps_per_sync", 4)),
        max_new_tokens=int(cfg.get("max_new_tokens", 8)),
        speculate_k=int(cfg.get("speculate_k", 0)),
        system_prefix=str(cfg.get("system_prefix", "")),
        extra_adapter_trials=list(cfg.get("extra_adapter_trials") or []),
        draft_trial_id=str(cfg.get("draft_trial_id", "")),
        draft_knobs=_require_dict_or_none(cfg.get("draft_knobs"),
                                          "draft_knobs"),
        kv_page_size=int(cfg.get("kv_page_size", 0)),
        kv_pages=int(cfg.get("kv_pages", 0)),
        paged_kernel=_tristate(cfg.get("paged_kernel")),
        default_slo=str(cfg.get("default_slo", "")),
        role=str(cfg.get("role", "")),
        host_kv_pages=int(cfg.get("host_kv_pages", 0)),
        kv_wait_s=float(cfg.get("kv_wait_s", 1.5)),
        pool_id=str(cfg.get("pool_id", "")))
    # observability sidecar: /metrics + /debug/requests on an ephemeral
    # (or configured) port, written to obs_port_file for the operator
    obs_host, obs_port = worker.serve_obs(
        cfg.get("obs_host", "127.0.0.1"), int(cfg.get("obs_port", 0)))
    if cfg.get("obs_port_file"):
        with open(cfg["obs_port_file"], "w") as f:
            f.write(str(obs_port))
    print(f"inference worker {worker.worker_id} serving "
          f"(obs on {obs_host}:{obs_port})", flush=True)
    worker.run()
    if worker.chaos_killed:
        # a chaos-killed worker must look ERRORED to the control plane
        # (non-zero rc → ServicesManager respawns it), not drained
        print(f"inference worker {worker.worker_id} chaos-killed",
              flush=True)
        return 31
    if worker.draining:
        print(f"inference worker {worker.worker_id} drained cleanly",
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
