"""Deterministic scale-out drill harness: stub engines, real plumbing.

Proving "N workers ≥ ~N× one worker at equal TTFT" with real LM engines
on the CPU-fallback rig is impossible — every in-process replica shares
one host CPU, so aggregate throughput is flat no matter how the router
spreads the streams. What the scale-out machinery actually needs proved
is *placement*: streams spread across the pool, shared prefixes
colocate, membership events (scale-up, drain-based scale-down, rolling
restart) never drop or duplicate a token. Those are properties of the
predictor/router/worker-loop plumbing, not of matmul throughput.

So the drill runs the REAL stack — :class:`InferenceWorker` serve
loops, the queue hub, the predictor's router/breaker/failover machinery
— over a **stub decode engine with an explicit capacity model**: each
engine step serves every live slot and costs
``base_step_s + per_req_step_s × live`` wall seconds (launch overhead +
per-request service time), so one worker's token throughput saturates
at ``1/per_req_step_s`` and capacity genuinely scales with engines, the
way separate accelerators do. Token text is a deterministic function of
(prompt, index), so any drop, duplication, or mis-resumed failover is a
hard string mismatch — the zero-token-loss proof needs no reference
run.

Used by ``tests/test_scaleout.py`` (tier-1 acceptance) and the
``bench_extra.py scaleout`` stage; results carry explicit
simulated-capacity provenance — they measure the routing/scaling plane,
never the kernels.
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..obs.metrics import StatsMap
from ..serving.predictor import Predictor, nearest_rank
from ..serving.queues import InProcQueueHub
from ..worker.inference import InferenceWorker


def stub_tokens(prompt: str, n: int) -> List[str]:
    """The deterministic token stream for ``prompt``: worker-independent
    (a failover must continue the same stream), prompt-unique (a stream
    answered with another prompt's tokens is a hard mismatch)."""
    h = hashlib.blake2b(prompt.encode("utf-8", "replace"),
                        digest_size=4).hexdigest()
    return [f"{h}t{i}" for i in range(n)]


def stub_completion(prompt: str, n: int) -> str:
    """The full expected completion text for ``prompt``."""
    return " ".join(stub_tokens(prompt, n))


class _StubReq:
    __slots__ = ("rid", "prompt", "start", "budget", "text", "n_out")

    def __init__(self, rid: Any, prompt: str, start: int, budget: int,
                 prefix: str) -> None:
        self.rid = rid
        self.prompt = prompt
        self.start = start      # first token index still to generate
        self.budget = budget    # total tokens incl. the forced prefix
        self.text = prefix      # accumulates prefix + delta strings
        self.n_out = 0          # tokens generated HERE (not the prefix)


class StubDecodeEngine:
    """Duck-typed decode engine with an explicit capacity model.

    Single-threaded by contract (submit/step/poll all run on the
    worker's serve-loop thread, like the real engine). Implements the
    exact surface ``InferenceWorker._run_decode_loop`` consumes: busy,
    step() → n_live, poll()/poll_partial(), stats (a StatsMap carrying
    the same ``kv_pages_used``/``admission_stalls`` gauges the paged
    engine publishes, so the router/autoscaler see real signals),
    span_sink lifecycle events, ``supports_resume`` + forced_prefix.
    """

    #: fake page accounting: slots-worth of pages so the ratio gauges
    #: behave like a paged pool under load
    PAGES_PER_SLOT = 4

    def __init__(self, max_slots: int = 8, max_new: int = 16,
                 base_step_s: float = 0.002,
                 per_req_step_s: float = 0.002) -> None:
        self.max_slots = int(max_slots)
        self.max_new = int(max_new)
        self.base_step_s = float(base_step_s)
        self.per_req_step_s = float(per_req_step_s)
        self.supports_resume = True
        self.span_sink = None
        self._live: "collections.OrderedDict[Any, _StubReq]" = \
            collections.OrderedDict()
        self._pending: "collections.deque[_StubReq]" = collections.deque()
        self._done: List[Tuple[Any, str]] = []
        self._partial: List[Tuple[Any, str]] = []
        self._pages_total = self.max_slots * self.PAGES_PER_SLOT
        self.stats = StatsMap({
            "tokens_generated": 0, "requests_done": 0, "steps": 0,
            "admission_stalls": 0, "max_concurrent": 0,
            "kv_pages_used": 0, "kv_pages_total": self._pages_total})

    # ---- the worker-loop surface ----
    @property
    def busy(self) -> bool:
        return bool(self._live or self._pending)

    def submit(self, rid: Any, text: str, max_new: Optional[int] = None,
               forced_prefix: str = "", **_samp: Any) -> None:
        budget = min(int(max_new) if max_new else self.max_new,
                     self.max_new)
        prefix = str(forced_prefix or "")
        start = len(prefix.split()) if prefix else 0
        req = _StubReq(rid, str(text), start, budget, prefix)
        if start >= budget:
            # the forced prefix already covers the whole budget: the
            # instant-done path (mirrors TextDecodeEngine)
            self._done.append((rid, prefix))
            return
        if len(self._live) < self.max_slots:
            self._admit(req)
        else:
            self.stats.inc("admission_stalls")
            self._pending.append(req)
        self._gauge_pages()

    def _admit(self, req: _StubReq) -> None:
        self._live[req.rid] = req
        if self.span_sink:
            self.span_sink("admitted", req.rid, {})

    def _gauge_pages(self) -> None:
        self.stats.set("kv_pages_used",
                       len(self._live) * self.PAGES_PER_SLOT)
        self.stats.max_set("max_concurrent", len(self._live))

    def _admit_pending(self) -> None:
        """Move queued requests into free slots (subclass hook: the
        SLO stub engine replaces the plain FIFO with the shared
        class-queue + preemption policy)."""
        while self._pending and len(self._live) < self.max_slots:
            self._admit(self._pending.popleft())

    def step(self) -> int:
        self._admit_pending()
        n = len(self._live)
        if n == 0:
            self._gauge_pages()
            return 0
        # THE capacity model: one fused step serves every live slot and
        # costs launch overhead + per-request service time — throughput
        # saturates at 1/per_req_step_s tokens/s per engine
        time.sleep(self.base_step_s + self.per_req_step_s * n)
        for rid, req in list(self._live.items()):
            i = req.start + req.n_out
            tok = stub_tokens(req.prompt, req.budget)[i]
            delta = tok if i == 0 else " " + tok
            req.text += delta
            req.n_out += 1
            self._partial.append((rid, delta))
            self.stats.inc("tokens_generated")
            if self.span_sink and i == 0:
                self.span_sink("first_token", rid, {})
            if req.start + req.n_out >= req.budget:
                del self._live[rid]
                self._done.append((rid, req.text))
                self.stats.inc("requests_done")
                if self.span_sink:
                    self.span_sink("done", rid, {"tokens": req.n_out})
        self.stats.inc("steps")
        self._gauge_pages()
        return n

    def poll(self) -> List[Tuple[Any, str]]:
        out, self._done = self._done, []
        return out

    def poll_partial(self) -> List[Tuple[Any, str]]:
        out, self._partial = self._partial, []
        return out

    def reset(self) -> None:
        self._live.clear()
        self._pending.clear()
        self._done.clear()
        self._partial.clear()
        self._gauge_pages()

    def reset_stats(self) -> None:
        """Post-warmup scrub: zero the traffic counters AND drop the
        warmup dummy's buffered deltas — its plain-string rid must
        never reach the serve loop's ``(mid, qi)`` unpack."""
        self._partial.clear()
        self.stats.reset(keep={"kv_pages_total": self._pages_total})

    def stats_snapshot(self) -> Dict[str, Any]:
        return self.stats.snapshot()


class StubLM:
    """Model-shaped shim so a real :class:`InferenceWorker` (serve
    loop, drain, stats publish, spans) can run a stub engine."""

    def __init__(self, **knobs: Any) -> None:
        self.knobs = dict(knobs)

    def load_parameters(self, _params: Any) -> None:
        pass

    def make_decode_engine(self, max_slots: int = 8,
                           max_new_tokens: int = 16,
                           steps_per_sync: int = 4,
                           **_extra: Any) -> StubDecodeEngine:
        return StubDecodeEngine(
            max_slots=max_slots, max_new=max_new_tokens,
            base_step_s=float(self.knobs.get("base_step_s", 0.002)),
            per_req_step_s=float(self.knobs.get("per_req_step_s",
                                                0.002)))


class ScaleoutHarness:
    """N real worker serve-loops over stub engines + one predictor with
    the affinity router, driven through membership events.

    Subclass hooks (the SLO overload harness rides them): ``MODEL_CLASS``
    picks the stub model every booted worker serves;
    ``_predictor_kwargs``/``_worker_kwargs`` extend the predictor /
    worker constructions."""

    MODEL_CLASS = StubLM

    def _predictor_kwargs(self) -> Dict[str, Any]:
        return {}

    def _worker_kwargs(self) -> Dict[str, Any]:
        return {}

    def __init__(self, n_workers: int, max_slots: int = 8,
                 max_new: int = 16, base_step_s: float = 0.002,
                 per_req_step_s: float = 0.002,
                 pool_id: str = "drill",
                 stream_silence_timeout_s: float = 5.0,
                 pool_refresh_every_s: float = 0.1) -> None:
        from ..store.param_store import ParamStore

        self.hub = InProcQueueHub()
        self.store = ParamStore.from_uri("mem://")
        self.store.save("stub", {})
        self.knobs = {"base_step_s": base_step_s,
                      "per_req_step_s": per_req_step_s}
        self.max_slots = max_slots
        self.max_new = max_new
        self.pool_id = pool_id
        self._version = 0.0
        self.workers: Dict[str, Tuple[InferenceWorker,
                                      threading.Thread]] = {}
        self._next = 0
        for _ in range(n_workers):
            self.add_worker(publish=False)
        self.pred = Predictor(
            self.hub, list(self.workers), gather_timeout=30.0,
            stream_silence_timeout_s=stream_silence_timeout_s,
            breaker_fail_threshold=3, pool_id=pool_id,
            **self._predictor_kwargs())
        # drill-speed refresh cadences (instance overrides of the
        # rate-limit floors; production keeps the class defaults)
        self.pred.POOL_REFRESH_EVERY_S = pool_refresh_every_s
        self.pred.LOAD_REFRESH_EVERY_S = pool_refresh_every_s
        self.publish()

    # ---- membership events ----
    def _boot(self, wid: str) -> None:
        w = InferenceWorker(self.MODEL_CLASS, "stub", self.knobs,
                            self.store, self.hub, wid,
                            decode_loop=True,
                            max_slots=self.max_slots,
                            max_new_tokens=self.max_new,
                            **self._worker_kwargs())
        th = threading.Thread(target=w.run, kwargs={"poll_timeout": 0.02},
                              daemon=True)
        th.start()
        self.workers[wid] = (w, th)

    def add_worker(self, publish: bool = True) -> str:
        """Scale-up: boot a fresh replica, then publish membership (the
        manager's warm-then-publish order)."""
        wid = f"sw-{self._next}"
        self._next += 1
        self._boot(wid)
        if publish:
            self.publish()
        return wid

    def drain_worker(self, wid: str, keep_in_pool: bool = False,
                     timeout: float = 30.0) -> None:
        """Scale-down (membership first, then graceful drain) or — with
        ``keep_in_pool`` — the drain half of a rolling restart."""
        w, th = self.workers.pop(wid)
        if not keep_in_pool:
            self.publish()
        w.drain()
        th.join(timeout=timeout)
        if th.is_alive():
            raise RuntimeError(f"worker {wid} did not drain")

    def rolling_restart(self, timeout: float = 30.0) -> None:
        """Drain → replace each worker one at a time under the SAME
        worker id (membership unchanged; the predictor re-admits each
        replacement from its fresh published stats)."""
        for wid in list(self.workers):
            self.drain_worker(wid, keep_in_pool=True, timeout=timeout)
            self._boot(wid)

    def publish(self) -> None:
        self._version = max(time.time(), self._version + 1e-4)
        self.hub.put_pool_members(self.pool_id, {
            "workers": list(self.workers), "version": self._version})

    def stop(self) -> None:
        for wid, (w, th) in list(self.workers.items()):
            w.stop()
            th.join(timeout=10)
        self.workers.clear()

    # ---- load driving / measurement ----
    def run_stream(self, prompt: str, timeout: float = 60.0
                   ) -> Dict[str, Any]:
        t0 = time.monotonic()
        ttft = None
        acc = ""
        final: Dict[str, Any] = {}
        for ev in self.pred.predict_stream([prompt], timeout=timeout):
            if "delta" in ev:
                if ttft is None:
                    ttft = time.monotonic() - t0
                acc += "".join(ev["delta"].values())
            elif "replace" in ev:
                acc = "".join(ev["replace"].values())
            if ev.get("done"):
                final = ev
        text = (final.get("predictions") or [""])[0] or ""
        expected = stub_completion(prompt, self.max_new)
        return {"ok": bool(text == expected == acc
                           and "error" not in final),
                "tokens": len(text.split()), "ttft_s": ttft,
                "total_s": time.monotonic() - t0,
                "failovers": (final.get("info") or {}).get("failovers",
                                                           0),
                "error": final.get("error"), "prompt": prompt}

    def run_load(self, prompts: Sequence[str], n_clients: int,
                 streams_per_client: int, timeout: float = 120.0,
                 on_half_done: Optional[Any] = None) -> Dict[str, Any]:
        """Drive ``n_clients`` concurrent stream clients round-robin
        over ``prompts``; returns aggregate throughput/latency plus the
        per-stream token-exactness verdict. ``on_half_done`` (a
        callable) fires once when half the streams completed — the hook
        the membership-cycle drill injects its events through."""
        results: List[Dict[str, Any]] = []
        lock = threading.Lock()
        fired = threading.Event()
        total = n_clients * streams_per_client

        def client(c: int) -> None:
            for k in range(streams_per_client):
                prompt = prompts[(c + k * n_clients) % len(prompts)]
                r = self.run_stream(prompt, timeout=timeout)
                with lock:
                    results.append(r)
                    half = len(results) >= total // 2
                if on_half_done is not None and half and \
                        not fired.is_set():
                    fired.set()
                    on_half_done()

        t0 = time.monotonic()
        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True)
                   for c in range(n_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout + 30)
        wall = time.monotonic() - t0
        ttfts = sorted(r["ttft_s"] for r in results
                       if r["ttft_s"] is not None)
        return {"streams": len(results),
                "ok": all(r["ok"] for r in results) and bool(results),
                "failures": [r for r in results if not r["ok"]],
                "tokens": sum(r["tokens"] for r in results),
                "tokens_per_s": (sum(r["tokens"] for r in results)
                                 / wall if wall > 0 else 0.0),
                "ttft_p50_s": nearest_rank(ttfts, 0.50),
                "ttft_p95_s": nearest_rank(ttfts, 0.95),
                "failovers": sum(int(r["failovers"] or 0)
                                 for r in results),
                "wall_s": wall}


def shared_prefix_prompts(n_groups: int, per_group: int,
                          prefix_chars: int = 64) -> List[str]:
    """Prompts in ``n_groups`` shared-prefix families, each prefix
    longer than the router's affinity key so every family maps to ONE
    key (the shared-system-prompt traffic shape)."""
    out = []
    for g in range(n_groups):
        prefix = f"sys{g:02d}-" * (prefix_chars // 6 + 2)
        for j in range(per_group):
            out.append(f"{prefix} user question {j}")
    return out
