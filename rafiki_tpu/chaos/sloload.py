"""Deterministic mixed-traffic overload harness: SLO drills on stubs.

The SLO story's acceptance property — "interactive p95 holds within
1.5× its unloaded value while best-effort throughput fills the
troughs" — is a property of the ADMISSION POLICY (class queues, aging,
preemption, shedding, brownout), not of matmul throughput, so like the
scale-out drills it runs on the :mod:`rafiki_tpu.chaos.scaleout`
capacity-model stack: REAL :class:`InferenceWorker` serve loops, the
real predictor (shed gate + brownout ladder), and a stub decode engine
whose step costs ``base + per_req × live`` wall seconds.

The one genuinely new piece is :class:`SloStubEngine`: the stub engine
running the SAME :class:`~rafiki_tpu.serving.slo.ClassQueue` policy
object the real :class:`~rafiki_tpu.serving.decode_engine.DecodeEngine`
uses — interactive-first admission, FIFO within class, aging
promotion (shielded from re-preemption), and youngest-lowest-class
preemption where the victim re-queues with its generated text as the
forced prefix, exactly the real engine's token-level move. Token text
stays a deterministic function of (prompt, index), so a preempted
stream that resumes with any token dropped, duplicated, or reordered
is a hard string mismatch — zero-loss preemption needs no reference
run. (Per-mode token-exactness of the REAL engine's preempt-resume is
tier-1 in ``tests/test_slo.py``; this harness proves the fleet-level
latency/shed/starvation properties.)

Used by ``tests/test_slo.py`` (tier-1 acceptance drill) and the
``bench_extra.py slo_overload`` stage; results carry explicit
simulated-capacity provenance.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..serving.predictor import nearest_rank
from ..serving.slo import ClassQueue, normalize_slo, preemption_victim
from .scaleout import (ScaleoutHarness, StubDecodeEngine, StubLM,
                       _StubReq, stub_completion)


class _SloReq(_StubReq):
    __slots__ = ("slo", "seq", "shielded")

    def __init__(self, rid: Any, prompt: str, start: int, budget: int,
                 prefix: str) -> None:
        super().__init__(rid, prompt, start, budget, prefix)
        self.slo = "interactive"
        self.seq = 0
        self.shielded = False


class SloStubEngine(StubDecodeEngine):
    """Class-aware stub engine: the real SLO admission policy over the
    scaleout capacity model. Single-threaded by contract like its
    parent, so the (caller-locked) :class:`ClassQueue` needs no lock
    here either."""

    supports_slo = True

    def __init__(self, max_slots: int = 8, max_new: int = 16,
                 base_step_s: float = 0.002,
                 per_req_step_s: float = 0.002,
                 aging_skips: int = ClassQueue.DEFAULT_AGING_SKIPS
                 ) -> None:
        super().__init__(max_slots=max_slots, max_new=max_new,
                         base_step_s=base_step_s,
                         per_req_step_s=per_req_step_s)
        self._cq = ClassQueue(aging_skips=aging_skips)
        self._seq = 0
        for k in ("preemptions", "slo_aged_promotions",
                  "queued_interactive", "queued_batch",
                  "queued_background"):
            self.stats.set(k, 0)

    def submit(self, rid: Any, text: str, max_new: Optional[int] = None,
               forced_prefix: str = "", slo: str = "",
               **_samp: Any) -> None:
        budget = min(int(max_new) if max_new else self.max_new,
                     self.max_new)
        prefix = str(forced_prefix or "")
        start = len(prefix.split()) if prefix else 0
        try:
            cls = normalize_slo(slo)
        except ValueError:
            cls = "interactive"  # worker-defensive, like the real loop
        if start >= budget:
            self._done.append((rid, prefix))
            return
        req = _SloReq(rid, str(text), start, budget, prefix)
        req.slo = cls
        self._seq += 1
        req.seq = self._seq
        self._cq.push(cls, req)

    def _preempt_for(self, cls: str) -> bool:
        """Evict one occupant via the SAME :func:`preemption_victim`
        policy the real engine runs (youngest lowest-class, shielded
        aged-promotions immune); the victim re-queues front-of-class
        with its emitted text as the forced prefix — the stub twin of
        the real engine's token-level preempt-resume. False when no
        victim ranks below ``cls``."""
        victim = preemption_victim(
            cls, [(rid, req.slo, req.seq, req.shielded)
                  for rid, req in self._live.items()])
        if victim is None:
            return False
        req = self._live.pop(victim)
        resumed = _SloReq(req.rid, req.prompt,
                          req.start + req.n_out, req.budget, req.text)
        resumed.slo = req.slo
        resumed.seq = req.seq
        resumed.shielded = req.shielded
        self._cq.push(req.slo, resumed, front=True)
        self.stats.inc("preemptions")
        if self.span_sink:
            self.span_sink("preempted", req.rid,
                           {"slo": req.slo, "by": cls,
                            "tokens": req.start + req.n_out})
        return True

    def _admit_pending(self) -> None:
        while True:
            nxt = self._cq.peek()
            if nxt is None:
                break
            cls, _head = nxt
            if len(self._live) >= self.max_slots and \
                    not self._preempt_for(cls):
                # full and nothing evictable: backpressure, visible on
                # the stall counter the router/autoscaler read
                self.stats.inc("admission_stalls")
                break
            _, req = self._cq.pop()
            if self._cq.last_pop_promoted:
                req.shielded = True  # aging fired: immune to eviction
            self._admit(req)
        for c, d in self._cq.depths().items():
            self.stats.set(f"queued_{c}", d)
        self.stats.set("slo_aged_promotions", self._cq.promotions)
        self._gauge_pages()

    @property
    def busy(self) -> bool:
        return bool(self._live or self._pending or self._cq)

    def reset(self) -> None:
        super().reset()
        self._cq.clear()


class SloStubLM(StubLM):
    """Model shim booting :class:`SloStubEngine` workers."""

    def make_decode_engine(self, max_slots: int = 8,
                           max_new_tokens: int = 16,
                           steps_per_sync: int = 4,
                           **_extra: Any) -> SloStubEngine:
        return SloStubEngine(
            max_slots=max_slots, max_new=max_new_tokens,
            base_step_s=float(self.knobs.get("base_step_s", 0.002)),
            per_req_step_s=float(self.knobs.get("per_req_step_s",
                                                0.002)),
            aging_skips=int(self.knobs.get(
                "aging_skips", ClassQueue.DEFAULT_AGING_SKIPS)))


class SloLoadHarness(ScaleoutHarness):
    """Mixed-traffic drill: real workers + predictor (shed gate,
    brownout ladder) over :class:`SloStubEngine` replicas."""

    MODEL_CLASS = SloStubLM

    def __init__(self, n_workers: int = 1,
                 shed_depths: Optional[Dict[str, int]] = None,
                 brownout_target_p95_s: float = 0.0,
                 brownout_clamp_max_new: int = 4,
                 aging_skips: int = ClassQueue.DEFAULT_AGING_SKIPS,
                 **kw: Any) -> None:
        self._pred_extra = {
            "slo_shed_depths": dict(shed_depths or {}),
            "brownout_target_p95_s": float(brownout_target_p95_s),
            "brownout_clamp_max_new": int(brownout_clamp_max_new)}
        self._aging_skips = int(aging_skips)
        super().__init__(n_workers, **kw)
        # drill-speed brownout ticks: the ladder rides the load
        # refresh, and a drill cannot wait a wall-clock second per tick
        self.pred.LOAD_REFRESH_EVERY_S = min(
            0.2, self.pred.LOAD_REFRESH_EVERY_S)

    def _predictor_kwargs(self) -> Dict[str, Any]:
        return dict(self._pred_extra)

    def _worker_kwargs(self) -> Dict[str, Any]:
        # every boot (initial or scale-up) sees the aging knob: the
        # hook runs before each worker construction
        self.knobs["aging_skips"] = getattr(
            self, "_aging_skips", ClassQueue.DEFAULT_AGING_SKIPS)
        return dict(super()._worker_kwargs())

    def _boot(self, wid: str) -> None:
        super()._boot(wid)
        # drill-speed stats publishes: the shed gate feeds on the
        # workers' published queued_* gauges, and a drill cannot wait
        # the production 50-iteration publish cadence
        self.workers[wid][0].STATS_EVERY = 2

    # ---- per-stream drive with an SLO class ----
    def run_slo_stream(self, prompt: str, slo: str = "interactive",
                       max_new: Optional[int] = None,
                       timeout: float = 60.0) -> Dict[str, Any]:
        """One stream of class ``slo``; verdicts: ``shed`` (structured
        refusal with ``retry_after_s``) or token-exactness of whatever
        was generated (``k`` tokens must be exactly
        ``stub_completion(prompt, k)`` — preemption/clamping may
        shorten a best-effort stream, never corrupt it)."""
        t0 = time.monotonic()
        ttft = None
        acc = ""
        final: Dict[str, Any] = {}
        for ev in self.pred.predict_stream(
                [prompt], timeout=timeout, slo=slo,
                sampling={"max_new": int(max_new)} if max_new else None):
            if "delta" in ev:
                if ttft is None:
                    ttft = time.monotonic() - t0
                acc += "".join(ev["delta"].values())
            elif "replace" in ev:
                acc = "".join(ev["replace"].values())
            if ev.get("done"):
                final = ev
        if final.get("shed"):
            return {"shed": True, "ok": True, "tokens": 0,
                    "ttft_s": None,
                    "retry_after_s": final.get("retry_after_s"),
                    "total_s": time.monotonic() - t0, "slo": slo,
                    "prompt": prompt}
        text = (final.get("predictions") or [""])[0] or ""
        k = len(text.split())
        budget = int(max_new) if max_new else self.max_new
        ok = bool(k >= 1 and k <= budget
                  and text == stub_completion(prompt, k)
                  and acc == text and "error" not in final)
        return {"shed": False, "ok": ok, "tokens": k, "ttft_s": ttft,
                "total_s": time.monotonic() - t0, "slo": slo,
                "error": final.get("error"), "prompt": prompt,
                "text": text}

    def run_mixed(self, spec: Dict[str, Dict[str, Any]],
                  timeout: float = 120.0) -> Dict[str, Dict[str, Any]]:
        """Drive concurrent per-class client pools. ``spec`` maps an
        SLO class to ``{clients, streams, max_new, think_s}``; returns
        per-class aggregates (token-exact verdict, shed count, TTFT
        p50/p95, throughput)."""
        results: Dict[str, List[Dict[str, Any]]] = {c: [] for c in spec}
        lock = threading.Lock()

        def client(cls: str, c: int, cfg: Dict[str, Any]) -> None:
            for k in range(int(cfg.get("streams", 1))):
                prompt = f"{cls} client {c} stream {k} prompt"
                r = self.run_slo_stream(
                    prompt, slo=cls, max_new=cfg.get("max_new"),
                    timeout=timeout)
                with lock:
                    results[cls].append(r)
                think = float(cfg.get("think_s", 0.0))
                if think > 0:
                    time.sleep(think)

        t0 = time.monotonic()
        threads = [threading.Thread(target=client, args=(cls, c, cfg),
                                    daemon=True)
                   for cls, cfg in spec.items()
                   for c in range(int(cfg.get("clients", 1)))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout + 30)
        wall = time.monotonic() - t0

        out: Dict[str, Dict[str, Any]] = {}
        for cls, rs in results.items():
            served = [r for r in rs if not r["shed"]]
            ttfts = sorted(r["ttft_s"] for r in served
                           if r["ttft_s"] is not None)
            out[cls] = {
                "streams": len(rs), "served": len(served),
                "shed": sum(1 for r in rs if r["shed"]),
                "shed_with_retry_hint": sum(
                    1 for r in rs if r["shed"]
                    and isinstance(r.get("retry_after_s"),
                                   (int, float))),
                "ok": bool(rs) and all(r["ok"] for r in rs),
                "failures": [r for r in rs if not r["ok"]],
                "tokens": sum(r["tokens"] for r in served),
                "tokens_per_s": (sum(r["tokens"] for r in served)
                                 / wall if wall > 0 else 0.0),
                "ttft_p50_s": nearest_rank(ttfts, 0.50),
                "ttft_p95_s": nearest_rank(ttfts, 0.95)}
        out["_wall_s"] = wall  # type: ignore[assignment]
        return out

    def engine_stats(self) -> Dict[str, Dict[str, Any]]:
        """Live per-worker engine counters (preemptions, queue depths,
        aged promotions) — the drill's policy-level evidence."""
        return {wid: w.engine.stats_snapshot()
                for wid, (w, _th) in self.workers.items()
                if w.engine is not None}


__all__ = ["SloLoadHarness", "SloStubEngine", "SloStubLM"]
