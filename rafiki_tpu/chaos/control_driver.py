"""A minimal REAL control-plane process for admin-kill drills.

The crash-recovery machinery (``ServicesManager.reconcile`` + the admin
lease) is exercised in-process by tier-1 tests, but the headline drill —
``kill -9`` the control plane under load, boot a second one, measure
time-to-reconverge — needs an actual process to kill. Booting the full
admin REST app for that means training a model to have something to
serve; this driver is the lighter harness: it builds a
:class:`ServicesManager` on a workdir, acquires the admin lease, starts
the kvd data plane, spawns N drainable dummy services against a RUNNING
inference job, writes a JSON ready-report, then loops ``poll()`` +
lease renewal until killed. A second boot with ``"mode": "reconcile"``
adopts the first driver's survivors and reports what it found.

Run: ``python -m rafiki_tpu.chaos.control_driver --config cfg.json``
with ``{workdir, db_path, n_services, ready_file,
mode: "boot"|"reconcile", lease_ttl_s}``. Used by
``bench_extra.py admin_recovery`` and the slow-tier recovery e2e test.
"""

from __future__ import annotations

import json
import time
from typing import Optional


def main(argv: Optional[list] = None) -> int:
    import argparse

    from ..admin.services_manager import LeaseHeldError, ServicesManager
    from ..constants import ServiceType
    from ..parallel.mesh import DeviceSpec
    from ..store.meta_store import MetaStore

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True)
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    t0 = time.monotonic()
    workdir = cfg["workdir"]
    n_services = int(cfg.get("n_services", 2))
    mode = cfg.get("mode", "boot")

    meta = MetaStore(cfg["db_path"])
    # virtual CPU devices: the drill is about process plumbing, not
    # chips — one slot per dummy service
    mgr = ServicesManager(
        meta, workdir, slot_size=1, platform="cpu",
        devices=[DeviceSpec(id=i) for i in range(max(1, n_services))])
    ttl_s = float(cfg.get("lease_ttl_s", 10.0))
    try:
        if mode == "reconcile":
            # restart-after-crash: the dead admin's lease expires one
            # TTL after its last heartbeat — retry like a supervisor
            # would instead of failing fast (the fail-fast path is for
            # DUPLICATE admins; a second live driver keeps renewing and
            # keeps this one out no matter how long we retry)
            deadline = time.monotonic() + ttl_s + 60.0
            while True:
                try:
                    lease = mgr.acquire_lease(ttl_s=ttl_s)
                    break
                except LeaseHeldError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.25)
        else:
            lease = mgr.acquire_lease(ttl_s=ttl_s)
    except LeaseHeldError as e:
        _report(cfg, {"error": "admin_lease_held", "detail": str(e)})
        return 3

    # heartbeat before reconcile, same as the real admin: a reconcile
    # longer than the TTL must not look like a dead holder
    mgr.start_lease_heartbeat()
    report = {"mode": mode, "pid_self": _pid(),
              "lease_generation": lease["generation"],
              "took_over": bool(lease.get("took_over"))}
    if mode == "reconcile":
        recovery = mgr.reconcile()
        report.update(recovery)
        report["adopted_pids"] = sorted(
            s.proc.pid for s in mgr.services.values())
        mgr.start_data_plane()  # no-op when the kvd was adopted
        report["kv_port"] = mgr.kv_port
    else:
        mgr.start_data_plane()
        # one RUNNING inference job to own the dummy "workers" (the
        # reconciler only adopts services whose job is still live)
        user = meta.create_user(f"drill-{_pid()}@chaos", "pw", "ADMIN")
        tj = meta.create_train_job(
            user["id"], f"chaos-drill-{_pid()}", 1,
            "LANGUAGE_MODELING", {"TRIAL_COUNT": 1}, "d1", "d2")
        ij = meta.create_inference_job(user["id"], tj["id"])
        meta.update_inference_job(ij["id"], status="RUNNING")
        pids = []
        for i in range(n_services):
            wid = f"drill-{i}"
            svc = mgr._spawn(
                "rafiki_tpu.chaos.dummy_service",
                {"worker_id": wid, "drain_linger_s": 0.2,
                 "obs_port_file": f"{workdir}/{wid}.obs_port"},
                ServiceType.INFERENCE_WORKER,
                slot=mgr.allocator.acquire(timeout=5.0),
                inference_job_id=ij["id"])
            pids.append(svc.proc.pid)
        # ready only once every dummy wrote its obs port (adoptable)
        deadline = time.monotonic() + 60
        import os.path

        while time.monotonic() < deadline and not all(
                os.path.exists(f"{workdir}/drill-{i}.obs_port")
                for i in range(n_services)):
            time.sleep(0.05)
        report.update({"spawned_pids": sorted(pids),
                       "kv_port": mgr.kv_port,
                       "inference_job_id": ij["id"]})
    report["boot_s"] = round(time.monotonic() - t0, 3)
    _report(cfg, report)
    print(f"control driver ready ({mode}): {report}", flush=True)

    # run until SIGTERM: poll children like the real admin monitor
    # (the lease heartbeat rides its own thread, started above)
    import signal
    import threading

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    while not stop.wait(0.5):
        if mgr.fenced:
            break  # a newer driver took over
        mgr.poll()
    mgr.stop_all()
    return 0


def _pid() -> int:
    import os

    return os.getpid()


def _report(cfg: dict, report: dict) -> None:
    path = cfg.get("ready_file")
    if path:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(report, f)
        import os

        os.replace(tmp, path)


if __name__ == "__main__":
    raise SystemExit(main())
