"""Deterministic fault injection for the serving data plane.

Fault tolerance that is only exercised by real outages is untested
code. This package injects the failure modes the request path claims to
survive — worker death mid-stream, dropped replies, delayed queues,
corrupted payloads — deterministically (seeded RNG, token-count
triggers), so tier-1 tests and the ``bench_extra failover`` stage can
drive every branch of the breaker/failover/drain machinery on demand.

Three pieces:

- :class:`ChaosConfig` — the injector knob set, parseable from the
  ``RAFIKI_CHAOS`` env var (``key=value`` pairs, comma/semicolon
  separated) so a real spawned worker process can be made faulty
  without code changes::

      RAFIKI_CHAOS="kill_after_tokens=32,seed=7"      # die mid-stream
      RAFIKI_CHAOS="drop_reply_p=0.2,delay_queue_s=0.05"

- :class:`ChaosInjector` — the seeded decision core + injection
  counters (a :class:`~rafiki_tpu.obs.metrics.StatsMap`, so injected
  faults are visible on the worker's ``/metrics`` as ``chaos_*``
  gauges: a chaos run is observable, not a mystery).

- :class:`ChaosHub` — a :class:`~rafiki_tpu.serving.queues.QueueHub`
  wrapper applying reply-drop / delay / corruption at the hub boundary;
  the kill-after-N-tokens trigger is threaded through the inference
  worker's decode loop instead (death is a worker behavior, not a
  queue one).

Injectors default to all-off; an all-off config costs nothing because
the worker only wraps its hub when at least one fault is armed.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, fields
from typing import Any, Dict, Optional

from ..obs.metrics import StatsMap
from ..serving.queues import QueueHub

#: the env var workers read at boot (see ChaosConfig.from_env)
CHAOS_ENV = "RAFIKI_CHAOS"


@dataclass
class ChaosConfig:
    """Injector knobs. All-off by default; every field is independent.

    - ``kill_after_tokens``: the worker dies (decode loop exits without
      replying or publishing, process exits non-zero) once its engine
      has generated this many tokens in total. The deterministic
      "worker killed mid-stream" trigger.
    - ``drop_reply_p``: each reply push (delta or final) is dropped
      with this probability — a lossy data plane / dying worker.
    - ``delay_queue_s``: every queue push sleeps this long first —
      transit latency / an overloaded hub.
    - ``corrupt_payload_p``: each reply push is bit-flipped with this
      probability — a torn write; consumers must fail structured, not
      crash.
    - ``kill_admin_after_s``: the ADMIN process SIGKILLs itself this
      many seconds after arming (:func:`arm_admin_kill` in the admin
      entrypoint) — the deterministic "control plane dies mid-load"
      drill behind the crash-recovery tests and the
      ``bench_extra admin_recovery`` stage. SIGKILL on purpose: no
      graceful-shutdown path may run, exactly like an OOM-kill or a
      host reboot.
    - ``delay_kv_transfer_s``: every KV page shipment push (prefill →
      decode worker, disaggregated serving) sleeps this long first — a
      slow interconnect / overloaded hub. The decode side must degrade
      to a local re-prefill when its wait window expires, not hang the
      stream.
    - ``drop_kv_page_p``: each KV page shipment is dropped entirely
      with this probability — a lost shipment. Same contract: the
      decode worker's wait window expires and it re-prefills locally
      (token-exact, just slower).
    - ``kill_kvd_after_s``: SIGKILL the kvd DATA-PLANE process this
      many seconds after arming (:func:`arm_kvd_kill` — the admin
      holds the kvd's pid) — the deterministic "data plane dies
      mid-load" drill behind the WAL-replay/respawn machinery and the
      ``bench_extra kvd_recovery`` stage. SIGKILL on purpose: the
      graceful-shutdown fsync must NOT run; recovery has to come from
      the WAL alone.
    - ``drop_hub_conn_p``: each hub RPC first force-closes the calling
      thread's kvd client socket with this probability — a per-RPC
      connection drop (flaky network, dying server). The reconnect
      layer must retry idempotently: no lost durable blob, no
      double-delivered queue message (dedup ids), blocking pops
      resumed.
    - ``seed``: drives every probabilistic draw; same seed + same
      traffic order = same faults.
    """

    kill_after_tokens: int = 0
    drop_reply_p: float = 0.0
    delay_queue_s: float = 0.0
    corrupt_payload_p: float = 0.0
    kill_admin_after_s: float = 0.0
    delay_kv_transfer_s: float = 0.0
    drop_kv_page_p: float = 0.0
    kill_kvd_after_s: float = 0.0
    drop_hub_conn_p: float = 0.0
    seed: int = 0

    @property
    def armed(self) -> bool:
        return bool(self.kill_after_tokens > 0 or self.drop_reply_p > 0
                    or self.delay_queue_s > 0
                    or self.corrupt_payload_p > 0
                    or self.kill_admin_after_s > 0
                    or self.delay_kv_transfer_s > 0
                    or self.drop_kv_page_p > 0
                    or self.kill_kvd_after_s > 0
                    or self.drop_hub_conn_p > 0)

    @classmethod
    def parse(cls, spec: str) -> "ChaosConfig":
        """``"kill_after_tokens=8,drop_reply_p=0.5,seed=3"`` → config.
        Unknown keys and malformed values raise: a chaos run with a
        typo'd knob silently testing nothing is worse than no run."""
        kw: Dict[str, Any] = {}
        casts = {f.name: f.type for f in fields(cls)}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep or key not in casts:
                raise ValueError(
                    f"unknown chaos knob {key!r} (have: "
                    f"{sorted(casts)})")
            cast = int if casts[key] in (int, "int") else float
            kw[key] = cast(val.strip())
        return cls(**kw)

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None
                 ) -> Optional["ChaosConfig"]:
        """The ``RAFIKI_CHAOS`` config, or None when unset/empty."""
        spec = (env if env is not None else os.environ).get(
            CHAOS_ENV, "").strip()
        if not spec:
            return None
        cfg = cls.parse(spec)
        return cfg if cfg.armed else None


def arm_admin_kill(cfg: ChaosConfig) -> Optional["object"]:
    """Arm the control-plane suicide timer: SIGKILL this process
    ``cfg.kill_admin_after_s`` seconds from now. Called by the admin
    entrypoint when chaos is armed; returns the started timer (or None
    when the knob is off) so a test can cancel it. SIGKILL — not
    SIGTERM — because the drill exists to prove recovery WITHOUT the
    graceful-shutdown path ever running."""
    if cfg.kill_admin_after_s <= 0:
        return None
    import os
    import signal
    import threading

    def _die() -> None:
        os.kill(os.getpid(), signal.SIGKILL)

    timer = threading.Timer(cfg.kill_admin_after_s, _die)
    timer.daemon = True
    timer.start()
    return timer


def arm_kvd_kill(cfg: ChaosConfig, get_pid,
                 injector: Optional["ChaosInjector"] = None
                 ) -> Optional["object"]:
    """Arm the data-plane kill timer: SIGKILL the kvd process
    ``cfg.kill_kvd_after_s`` seconds from now. ``get_pid`` is a
    zero-arg callable returning the kvd's CURRENT pid (the admin owns
    it; a callable, not a snapshot, so arming before the data plane
    boots still kills the right process). Returns the started timer
    (or None when the knob is off) so a test can cancel it. SIGKILL —
    not SHUTDOWN — because the drill exists to prove WAL replay,
    not the graceful-shutdown fsync."""
    if cfg.kill_kvd_after_s <= 0:
        return None
    import logging
    import os
    import signal
    import threading

    def _kill() -> None:
        pid = get_pid()
        if not pid:
            logging.getLogger(__name__).warning(
                "chaos kvd kill fired but no kvd pid is known")
            return
        if injector is not None:
            injector.counters.inc("kvd_kills")
        logging.getLogger(__name__).warning(
            "chaos: SIGKILLing kvd pid %d", pid)
        try:
            os.kill(int(pid), signal.SIGKILL)
        except OSError as e:
            logging.getLogger(__name__).warning(
                "chaos kvd kill of pid %s failed: %s", pid, e)

    timer = threading.Timer(cfg.kill_kvd_after_s, _kill)
    timer.daemon = True
    timer.start()
    return timer


class ChaosInjector:
    """Seeded decision core. One injector per faulty process; all
    decisions funnel through it so a (seed, traffic order) pair replays
    identically. Counters are exposed as ``chaos_*`` metrics by the
    owning worker."""

    def __init__(self, cfg: ChaosConfig) -> None:
        self.cfg = cfg
        self._rng = random.Random(cfg.seed)
        self.counters = StatsMap({"replies_dropped": 0,
                                  "payloads_corrupted": 0,
                                  "queue_delays": 0,
                                  "kills": 0,
                                  "kv_ships_dropped": 0,
                                  "kv_ship_delays": 0,
                                  "kvd_kills": 0,
                                  "hub_conn_drops": 0})

    def should_kill(self, tokens_generated: int) -> bool:
        """True once the cumulative generated-token count crosses the
        configured kill point (then latched: a killed worker stays
        killed)."""
        k = self.cfg.kill_after_tokens
        if k <= 0 or tokens_generated < k:
            return False
        if not self.counters["kills"]:
            self.counters.inc("kills")
        return True

    def mangle_reply(self, data: bytes) -> Optional[bytes]:
        """Apply drop/corrupt faults to a reply payload: None = dropped,
        otherwise the (possibly corrupted) bytes to push."""
        if self.cfg.drop_reply_p > 0 and \
                self._rng.random() < self.cfg.drop_reply_p:
            self.counters.inc("replies_dropped")
            return None
        if self.cfg.corrupt_payload_p > 0 and \
                self._rng.random() < self.cfg.corrupt_payload_p:
            self.counters.inc("payloads_corrupted")
            if data:
                i = self._rng.randrange(len(data))
                data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        return data

    def maybe_delay(self) -> None:
        d = self.cfg.delay_queue_s
        if d > 0:
            self.counters.inc("queue_delays")
            time.sleep(d)

    def should_drop_conn(self) -> bool:
        """Seeded per-RPC connection-drop decision (the fault behind
        ``drop_hub_conn_p``); counted so a chaos run's /metrics shows
        how many drops actually fired."""
        if self.cfg.drop_hub_conn_p <= 0:
            return False
        if self._rng.random() >= self.cfg.drop_hub_conn_p:
            return False
        self.counters.inc("hub_conn_drops")
        return True

    def mangle_kv_ship(self, data: bytes) -> Optional[bytes]:
        """Apply the KV-shipment faults: None = shipment dropped (the
        decode worker's wait window expires → local re-prefill);
        otherwise the bytes to push, after any configured transfer
        delay."""
        if self.cfg.drop_kv_page_p > 0 and \
                self._rng.random() < self.cfg.drop_kv_page_p:
            self.counters.inc("kv_ships_dropped")
            return None
        if self.cfg.delay_kv_transfer_s > 0:
            self.counters.inc("kv_ship_delays")
            time.sleep(self.cfg.delay_kv_transfer_s)
        return data


class ChaosHub(QueueHub):
    """A :class:`QueueHub` decorator applying the injector's queue
    faults. Reply/shipment faults live on the PUSH side (a worker
    failing to get its answer out), which is where the breaker/failover
    machinery must catch them; the per-RPC connection-drop fault
    (``drop_hub_conn_p``) applies to EVERY hub op — it force-closes the
    inner hub's thread-local socket right before the call, so the op
    itself lands on a dead transport and must come back through the
    reconnect + idempotent-replay layer. On a socketless inner hub
    (in-proc) the drop is a counted no-op."""

    def __init__(self, inner: QueueHub, injector: ChaosInjector) -> None:
        self.inner = inner
        self.injector = injector

    def _maybe_drop_conn(self) -> None:
        if self.injector.should_drop_conn():
            drop = getattr(self.inner, "drop_conn", None)
            if drop is not None:
                drop()

    def push_query(self, worker_id: str, data: bytes) -> None:
        self.injector.maybe_delay()
        self._maybe_drop_conn()
        self.inner.push_query(worker_id, data)

    def pop_query(self, worker_id: str, timeout: float):
        self._maybe_drop_conn()
        return self.inner.pop_query(worker_id, timeout)

    def push_prediction(self, query_id: str, data: bytes) -> None:
        self.injector.maybe_delay()
        mangled = self.injector.mangle_reply(data)
        if mangled is None:
            return  # dropped on the floor — the fault being injected
        self._maybe_drop_conn()
        self.inner.push_prediction(query_id, mangled)

    def pop_prediction(self, query_id: str, timeout: float):
        self._maybe_drop_conn()
        return self.inner.pop_prediction(query_id, timeout)

    def query_depth(self, worker_id: str) -> int:
        self._maybe_drop_conn()
        return self.inner.query_depth(worker_id)

    def discard_prediction_queue(self, query_id: str) -> None:
        self._maybe_drop_conn()
        self.inner.discard_prediction_queue(query_id)

    def arm_reply_ttl(self, query_id: str, ttl_s: float) -> None:
        self._maybe_drop_conn()
        self.inner.arm_reply_ttl(query_id, ttl_s)

    def put_worker_stats(self, worker_id: str, stats) -> None:
        self._maybe_drop_conn()
        self.inner.put_worker_stats(worker_id, stats)

    def get_worker_stats(self, worker_id: str):
        self._maybe_drop_conn()
        return self.inner.get_worker_stats(worker_id)

    def put_pool_members(self, pool_id: str, members) -> None:
        self._maybe_drop_conn()
        self.inner.put_pool_members(pool_id, members)

    def get_pool_members(self, pool_id: str):
        self._maybe_drop_conn()
        return self.inner.get_pool_members(pool_id)

    def push_kv(self, worker_id: str, data: bytes) -> None:
        mangled = self.injector.mangle_kv_ship(data)
        if mangled is None:
            return  # the lost shipment being injected: the decode
            #         side's wait window expires → local re-prefill
        self._maybe_drop_conn()
        self.inner.push_kv(worker_id, mangled)

    def pop_kv(self, worker_id: str, timeout: float):
        self._maybe_drop_conn()
        return self.inner.pop_kv(worker_id, timeout)

    def kv_depth(self, worker_id: str) -> int:
        self._maybe_drop_conn()
        return self.inner.kv_depth(worker_id)

    def put_blob(self, key: str, data: bytes) -> None:
        self._maybe_drop_conn()
        self.inner.put_blob(key, data)

    def get_blob(self, key: str):
        self._maybe_drop_conn()
        return self.inner.get_blob(key)

    def drop_conn(self) -> None:
        """Pass-through so stacked decorators keep the chaos surface."""
        drop = getattr(self.inner, "drop_conn", None)
        if drop is not None:
            drop()


__all__ = ["CHAOS_ENV", "ChaosConfig", "ChaosHub", "ChaosInjector",
           "arm_admin_kill", "arm_kvd_kill"]
