"""A drainable stub worker for chaos / rolling-restart harness tests.

``ServicesManager.rolling_restart`` orchestrates drain → exit → respawn
over real child processes. Exercising that orchestration with a real
inference worker means training + loading a model per test — minutes of
setup to test process plumbing. This stub speaks exactly the two
protocols the manager relies on and nothing else:

- it writes its obs port to ``obs_port_file`` (like a real worker's
  sidecar) and serves ``POST /drain``;
- on drain it exits 0 after ``drain_linger_s`` (simulating "finish
  in-flight work, then leave").

Run: ``python -m rafiki_tpu.chaos.dummy_service --config cfg.json`` with
``{"worker_id", "obs_port_file", "drain_linger_s"}``.
"""

from __future__ import annotations

import json
import threading
from typing import Optional


def main(argv: Optional[list] = None) -> int:
    import argparse

    from ..utils.http import JsonHttpService

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True)
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    linger = float(cfg.get("drain_linger_s", 0.0))
    done = threading.Event()

    def _drain(_m, _b, _h):
        # reply first, exit after: the draining worker must stay
        # reachable long enough to acknowledge the drain request
        threading.Timer(max(0.05, linger), done.set).start()
        return 200, {"ok": True, "draining": True}

    http = JsonHttpService("127.0.0.1", int(cfg.get("obs_port", 0)))
    http.route("POST", "/drain", _drain)
    http.route("GET", "/health",
               lambda _m, _b, _h: (200, {"ok": True}))
    _, port = http.start()
    if cfg.get("obs_port_file"):
        with open(cfg["obs_port_file"], "w") as f:
            f.write(str(port))
    print(f"dummy service {cfg.get('worker_id', '?')} on :{port}",
          flush=True)
    done.wait()
    http.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
