"""rafiki-tpu: a TPU-native AutoML train-and-serve framework.

A ground-up rebuild of the capabilities of Rafiki (``ZhaoxuanWu/rafiki``,
"Rafiki: Machine Learning as an Analytics Service System", VLDB 2018) on a
JAX/XLA/Pallas substrate: model templates are JAX modules compiled with
``jit``/``pjit``; trials are processes pinned to ICI-contiguous TPU
sub-meshes instead of one-GPU Docker containers; serving uses continuous
batching with bucketed static shapes on TPU. See SURVEY.md for the
structural map of the reference this tracks.
"""

__version__ = "0.3.0"

from .constants import (BudgetOption, InferenceJobStatus, ServiceStatus,
                        ServiceType, TaskType, TrainJobStatus, TrialStatus,
                        UserType)

__all__ = [
    "BudgetOption", "InferenceJobStatus", "ServiceStatus", "ServiceType",
    "TaskType", "TrainJobStatus", "TrialStatus", "UserType", "__version__",
]
