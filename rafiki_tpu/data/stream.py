"""Constant-memory streaming image loader — the ImageNet-scale input
pipeline (BASELINE.md config #2).

``load_image_classification_dataset`` decodes an entire archive into one
host array — right for tuning-trial datasets, impossible for ImageNet
(~150 GB raw). This loader streams the same layouts (``.zip`` of images
+ ``labels.csv``, or a directory with ``labels.csv``) with a bounded
footprint:

- **Index pass** reads only ``labels.csv``: names + labels + class set.
  Image bytes are touched exactly when their sample is scheduled.
- **Worker-thread decode**: a pool decodes/augments samples ahead of the
  consumer through a sliding window of futures — at most
  ``prefetch_batches × batch_size`` decoded samples exist at once, so
  host memory is constant in dataset size. (Thread, not process,
  workers: PIL decode and numpy releases the GIL; the consumer is the
  TPU feed which is IO-bound anyway.) Each worker holds its own zip
  handle — ``ZipFile`` reads are not thread-safe on a shared one.
- **Augmentation** (train-time): pad-4-reflect random crop + horizontal
  flip, the classic CNN recipe. Per-sample determinism: the RNG is
  seeded by (seed, epoch, sample index), so a resumed/re-run epoch sees
  identical pixels regardless of worker scheduling.
- Batches come out shape-static (``batch_size`` rows + validity mask),
  ready for the same ``prefetch_to_device`` path the in-memory loader
  feeds.

Members may be PNG/JPEG (PIL) or raw ``.npy`` arrays. All images must
share one shape (resize upstream — a resize-on-decode hook is a
one-liner in ``_decode`` when a mixed-size corpus shows up).
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import csv
import io
import os
import threading
import zipfile
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

#: datasets at or above this size stream by default in the CNN templates
#: (below it, whole-array in-memory training is faster and simpler)
STREAM_THRESHOLD_MB = float(os.environ.get("RAFIKI_STREAM_THRESHOLD_MB",
                                           "512"))


def dataset_size_bytes(path: str) -> int:
    p = Path(path)
    if p.is_file():
        return p.stat().st_size
    if p.is_dir():
        return sum(f.stat().st_size for f in p.rglob("*") if f.is_file())
    return 0


def should_stream(path: str) -> bool:
    """Template-side policy: stream when the archive is big enough that
    whole-array loading would hurt, or when forced (tests/benches)."""
    if os.environ.get("RAFIKI_FORCE_STREAMING") == "1":
        return True
    return dataset_size_bytes(path) >= STREAM_THRESHOLD_MB * 2 ** 20


class StreamingImageDataset:
    """Streaming reader over a zip/dir image-classification dataset."""

    def __init__(self, path: str, n_workers: int = 4,
                 prefetch_batches: int = 4) -> None:
        self.path = str(path)
        self.n_workers = max(1, int(n_workers))
        self.prefetch_batches = max(1, int(prefetch_batches))
        p = Path(self.path)
        self._is_zip = p.is_file() and p.suffix == ".zip"
        if not self._is_zip and not (p.is_dir()
                                     and (p / "labels.csv").exists()):
            raise ValueError(
                f"not a streamable dataset (zip or dir with labels.csv):"
                f" {path!r}")
        self._tl = threading.local()  # per-worker zip handles
        names, labels = self._read_index()
        from .dataset import _labels_to_ids  # shared class-id mapping

        self.names: List[str] = names
        self.labels, self.classes = _labels_to_ids(labels)
        self.n = len(names)
        self.n_classes = len(self.classes)
        first = self._decode(self.names[0])
        self.image_shape: Tuple[int, ...] = tuple(first.shape)

    @staticmethod
    def is_streamable(path: str) -> bool:
        p = Path(path)
        return (p.is_file() and p.suffix == ".zip") or \
            (p.is_dir() and (p / "labels.csv").exists())

    # ---- io ----
    def _zip(self) -> zipfile.ZipFile:
        zf = getattr(self._tl, "zf", None)
        if zf is None:
            zf = self._tl.zf = zipfile.ZipFile(self.path)
        return zf

    def _read_index(self) -> Tuple[List[str], List[str]]:
        # same parser as the in-memory loader — the two paths must never
        # disagree on header handling or row filtering for one archive
        from .dataset import _read_labels_csv

        if self._is_zip:
            with self._zip().open("labels.csv") as f:
                rows = _read_labels_csv(f)
        else:
            with open(Path(self.path) / "labels.csv") as f:
                rows = _read_labels_csv(f)
        if not rows:
            raise ValueError(f"{self.path}: empty labels.csv")
        return [r[0] for r in rows], [r[1] for r in rows]

    def _read_bytes(self, name: str) -> bytes:
        if self._is_zip:
            return self._zip().read(name)
        return (Path(self.path) / name).read_bytes()

    def _decode(self, name: str) -> np.ndarray:
        data = self._read_bytes(name)
        if name.endswith(".npy"):
            arr = np.load(io.BytesIO(data), allow_pickle=False)
        else:
            from PIL import Image

            arr = np.asarray(Image.open(io.BytesIO(data)))
        arr = np.asarray(arr, np.uint8)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr

    # ---- augmentation ----
    @staticmethod
    def _augment(img: np.ndarray, rng: np.random.Generator,
                 pad: int = 4) -> np.ndarray:
        h, w = img.shape[:2]
        if rng.random() < 0.5:
            img = img[:, ::-1]  # horizontal flip
        padded = np.pad(img, ((pad, pad), (pad, pad), (0, 0)),
                        mode="reflect")
        top = int(rng.integers(0, 2 * pad + 1))
        left = int(rng.integers(0, 2 * pad + 1))
        return padded[top:top + h, left:left + w]

    def _load_one(self, i: int, epoch: int, seed: int,
                  augment: bool) -> np.ndarray:
        img = self._decode(self.names[i])
        if augment:
            # keyed by (seed, epoch, index): augmentation is a pure
            # function of the sample's identity, not worker scheduling
            rng = np.random.default_rng((seed, epoch, i))
            img = self._augment(img, rng)
        return np.ascontiguousarray(img)

    # ---- iteration ----
    def _ordered_samples(self, order: Sequence[int], epoch: int,
                         seed: int, augment: bool,
                         batch_size: int) -> Iterator[Tuple[int,
                                                            np.ndarray]]:
        # the documented host-memory bound: at most prefetch_batches
        # batches' worth of decoded samples in flight
        window = max(self.n_workers, self.prefetch_batches * batch_size)
        with cf.ThreadPoolExecutor(self.n_workers) as ex:
            pending: "collections.deque" = collections.deque()
            it = iter(order)

            def submit_next() -> bool:
                try:
                    i = next(it)
                except StopIteration:
                    return False
                pending.append((i, ex.submit(self._load_one, int(i),
                                             epoch, seed, augment)))
                return True

            for _ in range(window):
                if not submit_next():
                    break
            while pending:
                i, fut = pending.popleft()
                submit_next()
                yield int(i), fut.result()

    def iter_batches(self, batch_size: int, epoch: int = 0,
                     shuffle: bool = True, seed: int = 0,
                     augment: bool = False,
                     drop_remainder: bool = False
                     ) -> Iterator[Dict[str, np.ndarray]]:
        """Shape-static batches ``{"x": uint8 (B,H,W,C), "y": int32,
        "mask": bool}``; the final partial batch pads by repeating its
        first row, masked out."""
        rng = np.random.default_rng((seed, epoch))
        order = rng.permutation(self.n) if shuffle else np.arange(self.n)
        buf_x: List[np.ndarray] = []
        buf_y: List[int] = []

        def emit(valid: int) -> Dict[str, np.ndarray]:
            x = np.stack(buf_x + [buf_x[0]] * (batch_size - valid))
            y = np.asarray(buf_y + [buf_y[0]] * (batch_size - valid),
                           np.int32)
            mask = np.arange(batch_size) < valid
            return {"x": x, "y": y, "mask": mask}

        for i, img in self._ordered_samples(order, epoch, seed, augment,
                                            batch_size):
            buf_x.append(img)
            buf_y.append(int(self.labels[i]))
            if len(buf_x) == batch_size:
                yield emit(batch_size)
                buf_x, buf_y = [], []
        if buf_x and not drop_remainder:
            yield emit(len(buf_x))


def generate_streaming_image_zip(path: str, n: int,
                                 image_shape: Tuple[int, int, int]
                                 = (32, 32, 3),
                                 n_classes: int = 4, seed: int = 0,
                                 fmt: str = "png") -> None:
    """Synthetic class-separable zip dataset in the streamable layout
    (images + labels.csv). ``fmt``: ``png`` (exercises PIL decode) or
    ``npy`` (raw arrays — decode-cheap, for throughput benches)."""
    rng = np.random.default_rng(seed)
    h, w, c = image_shape
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        rows = ["path,label"]
        for i in range(n):
            label = int(rng.integers(n_classes))
            # one bright quadrant per class + noise: learnable signal
            img = rng.integers(0, 96, size=(h, w, c)).astype(np.uint8)
            qh, qw = h // 2, w // 2
            top, left = (label // 2) * qh, (label % 2) * qw
            img[top:top + qh, left:left + qw] = np.minimum(
                img[top:top + qh, left:left + qw] + 140, 255)
            name = f"img{i:06d}.{fmt}"
            buf = io.BytesIO()
            if fmt == "npy":
                np.save(buf, img, allow_pickle=False)
            else:
                from PIL import Image

                Image.fromarray(img).save(buf, format=fmt.upper())
            zf.writestr(name, buf.getvalue())
            rows.append(f"{name},c{label}")
        zf.writestr("labels.csv", "\n".join(rows))
