"""Trainable byte-level BPE tokenizer — the real-tokenizer leg of
BASELINE.md config #5.

The reference stack gets its tokenizers from upstream model hubs; this
environment has zero egress, so the framework ships a self-contained
byte-level BPE (the GPT-2/Llama family's algorithm): train on any local
corpus, save the merge table as a JSON artifact, load it anywhere. The
``HashTokenizer`` (models/bert.py) remains the zero-setup default for
tuning runs; BPE is what serving-quality LM work (and pretrained-weight
import, models/convert.py) plugs in via the ``tokenizer_path`` knob.

Design points:
- **Byte-level, lossless.** The base vocabulary is all 256 bytes;
  arbitrary unicode round-trips exactly (``decode(encode(s)) == s``)
  with no unknown-token escape hatch needed.
- **Pre-tokenization** splits text into chunks of "optional single
  leading space + non-space run" or whitespace runs; merges never cross
  chunk boundaries (the standard trick that keeps merge statistics
  word-shaped and encoding parallelizable).
- **Id layout**: 0..N_SPECIAL-1 specials (PAD=0, BOS=1, EOS=2 — PAD/BOS
  match the HashTokenizer contract so templates swap tokenizers without
  re-learning id conventions), then the 256 byte tokens, then one id
  per merge in training order.
- Training is the classic greedy loop (count adjacent pairs over the
  word histogram, merge the most frequent, repeat) — O(merges × unique
  words), plenty for corpus files in the tens of MB this framework
  trains on locally.
"""

from __future__ import annotations

import json
import re
from functools import lru_cache
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
N_SPECIAL = 3
_N_BYTES = 256

#: chunker: a word keeps one leading space; other whitespace runs stand
#: alone. Chunks partition the text, so concatenating decoded chunks
#: reproduces it byte-for-byte.
_CHUNK_RE = re.compile(r" ?[^\s]+|\s+")


class _NativeBPE:
    """ctypes handle over ``native/bpe_encoder.cc`` (built on demand by
    the native Makefile, like ``rafiki-kvd``). Holds the library AND
    the encoder handle so lifetime is tied to the tokenizer."""

    _lib = None       # process-wide loaded library (single slot)
    _lib_key = None   # (path, mtime_ns) the slot was loaded from —
    #                   a rebuild (atomic rename → new inode/mtime)
    #                   forces a fresh CDLL instead of stale code

    def __init__(self, lib, handle) -> None:
        self._l = lib
        self._h = handle

    def __del__(self) -> None:  # best-effort; process exit also frees
        try:
            self._l.rbpe_free(self._h)
        except Exception:  # rafiki: noqa[silent-except] — interpreter
            pass           # teardown; nowhere left to report to

    def encode_chunk(self, chunk: bytes) -> Tuple[int, ...]:
        import ctypes

        n = len(chunk)
        out = (ctypes.c_int32 * max(n, 1))()
        got = self._l.rbpe_encode_chunk(
            self._h, ctypes.c_char_p(chunk), n, out, max(n, 1))
        if got < 0:  # cannot happen (merges only shrink) — but never
            raise RuntimeError("native bpe buffer overflow")  # corrupt
        return tuple(out[:got])


def _native_encoder(merges) -> "_NativeBPE | None":
    """Load (building if needed) the native chunk encoder, or None when
    disabled/unbuildable — the Python loop is always a valid twin."""
    import os

    if os.environ.get("RAFIKI_NATIVE_BPE", "").lower() in ("off", "0"):
        return None
    try:
        import ctypes

        from rafiki_tpu.native.client import ensure_built

        lib_path = ensure_built(target="librbpe.so")
        key = (str(lib_path), lib_path.stat().st_mtime_ns)
        if _NativeBPE._lib is None or _NativeBPE._lib_key != key:
            lib = ctypes.CDLL(str(lib_path))
            lib.rbpe_create.restype = ctypes.c_void_p
            lib.rbpe_create.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
            lib.rbpe_free.argtypes = [ctypes.c_void_p]
            lib.rbpe_encode_chunk.restype = ctypes.c_int32
            lib.rbpe_encode_chunk.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
            _NativeBPE._lib, _NativeBPE._lib_key = lib, key
        lib = _NativeBPE._lib
        flat = [x for pair in merges for x in pair]
        arr = (ctypes.c_int32 * len(flat))(*flat) if flat else \
            (ctypes.c_int32 * 1)()
        handle = lib.rbpe_create(arr, len(merges))
        if not handle:
            return None
        return _NativeBPE(lib, ctypes.c_void_p(handle))
    except Exception as e:  # noqa: BLE001 — Python twin is always valid
        import logging
        import subprocess

        detail = ""
        if isinstance(e, subprocess.CalledProcessError) and e.stderr:
            detail = ": " + e.stderr.decode("utf-8", "replace")[-400:]
        # observable, not fatal: silent fallback would show up only as
        # unexplained serving-host latency
        logging.getLogger(__name__).warning(
            "native BPE encoder unavailable (%s%s); using the Python "
            "merge loop", e, detail)
        return None


class ByteBPETokenizer:
    """Byte-level BPE with a JSON-artifact merge table.

    Mirrors the ``HashTokenizer`` call surface (``encode(text, max_len)
    -> (row, n)`` with a leading BOS, ``encode_batch``, ``vocab_size``)
    and adds what hashing can't do: exact ``decode``.
    """

    def __init__(self, merges: Sequence[Tuple[int, int]]) -> None:
        #: merge table in training order; merge i creates token id
        #: N_SPECIAL + 256 + i from its (left, right) pair
        self.merges: List[Tuple[int, int]] = [tuple(m) for m in merges]
        self._rank: Dict[Tuple[int, int], int] = {
            m: i for i, m in enumerate(self.merges)}
        #: id → byte string (specials decode to b"")
        self._bytes: List[bytes] = [b""] * N_SPECIAL + [
            bytes([i]) for i in range(_N_BYTES)]
        for left, right in self.merges:
            self._bytes.append(self._bytes[left] + self._bytes[right])
        #: native chunk encoder (ctypes over native/bpe_encoder.cc) —
        #: the merge loop is the serving host path's CPU hotspot; the
        #: C++ twin is algorithm-identical (tests assert id-for-id
        #: parity) and the Python loop remains the fallback.
        #: RAFIKI_NATIVE_BPE=off disables.
        self._native = _native_encoder(self.merges)
        impl = (self._native.encode_chunk if self._native is not None
                else self._bpe_chunk)
        self._encode_chunk = lru_cache(maxsize=65536)(impl)

    @property
    def vocab_size(self) -> int:
        return N_SPECIAL + _N_BYTES + len(self.merges)

    # ---- encoding ----
    def _bpe_chunk(self, chunk: bytes) -> Tuple[int, ...]:
        ids = [N_SPECIAL + b for b in chunk]
        while len(ids) > 1:
            best, best_rank = None, None
            for pair in zip(ids, ids[1:]):
                r = self._rank.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = pair, r
            if best is None:
                break
            merged = N_SPECIAL + _N_BYTES + best_rank
            out: List[int] = []
            i = 0
            while i < len(ids):
                if i + 1 < len(ids) and (ids[i], ids[i + 1]) == best:
                    out.append(merged)
                    i += 2
                else:
                    out.append(ids[i])
                    i += 1
            ids = out
        return tuple(ids)

    def encode_ids(self, text: str) -> List[int]:
        """Token ids for ``text`` (no BOS, no padding)."""
        out: List[int] = []
        for chunk in _CHUNK_RE.findall(text):
            out.extend(self._encode_chunk(chunk.encode("utf-8")))
        return out

    def encode(self, text: str, max_len: int) -> Tuple[List[int], int]:
        """HashTokenizer-compatible: (ids padded to ``max_len`` with a
        leading BOS, true length including BOS)."""
        ids = [BOS_ID] + self.encode_ids(text)[:max_len - 1]
        length = len(ids)
        return ids + [PAD_ID] * (max_len - length), length

    def encode_batch(self, texts: Sequence[str],
                     max_len: int) -> Tuple[np.ndarray, np.ndarray]:
        ids = np.zeros((len(texts), max_len), np.int32)
        lens = np.zeros((len(texts),), np.int32)
        for i, t in enumerate(texts):
            row, n = self.encode(t, max_len)
            ids[i], lens[i] = row, n
        return ids, lens

    def decode(self, ids: Iterable[int]) -> str:
        """Exact inverse of ``encode_ids`` (specials vanish; invalid
        UTF-8 from truncated multi-byte tokens is replaced)."""
        data = b"".join(self._bytes[i] for i in ids
                        if 0 <= int(i) < len(self._bytes))
        return data.decode("utf-8", errors="replace")

    # ---- artifact ----
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "rafiki-bpe-v1",
                       "merges": [list(m) for m in self.merges]}, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPETokenizer":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != "rafiki-bpe-v1":
            raise ValueError(f"{path}: not a rafiki-bpe-v1 artifact")
        return cls([tuple(m) for m in blob["merges"]])

    # ---- training ----
    @classmethod
    def train(cls, corpus: Iterable[str],
              vocab_size: int) -> "ByteBPETokenizer":
        """Learn merges from text lines until ``vocab_size`` is reached
        (or no pair repeats). Deterministic: ties break on the
        lexicographically smallest pair."""
        n_merges = vocab_size - N_SPECIAL - _N_BYTES
        if n_merges < 0:
            raise ValueError(
                f"vocab_size must be ≥ {N_SPECIAL + _N_BYTES}")
        # word histogram: merge statistics over unique chunks
        words: Dict[Tuple[int, ...], int] = {}
        for line in corpus:
            for chunk in _CHUNK_RE.findall(line):
                key = tuple(N_SPECIAL + b for b in chunk.encode("utf-8"))
                if key:
                    words[key] = words.get(key, 0) + 1
        merges: List[Tuple[int, int]] = []
        for _ in range(n_merges):
            counts: Dict[Tuple[int, int], int] = {}
            for word, freq in words.items():
                for pair in zip(word, word[1:]):
                    counts[pair] = counts.get(pair, 0) + freq
            if not counts:
                break
            best = max(counts, key=lambda p: (counts[p], (-p[0], -p[1])))
            if counts[best] < 2:
                break  # nothing repeats — more merges would memorize
            new_id = N_SPECIAL + _N_BYTES + len(merges)
            merges.append(best)
            new_words: Dict[Tuple[int, ...], int] = {}
            for word, freq in words.items():
                out: List[int] = []
                i = 0
                while i < len(word):
                    if i + 1 < len(word) and \
                            (word[i], word[i + 1]) == best:
                        out.append(new_id)
                        i += 2
                    else:
                        out.append(word[i])
                        i += 1
                key = tuple(out)
                new_words[key] = new_words.get(key, 0) + freq
            words = new_words
        return cls(merges)

    @classmethod
    def train_file(cls, corpus_path: str,
                   vocab_size: int) -> "ByteBPETokenizer":
        with open(corpus_path, encoding="utf-8") as f:
            return cls.train(f, vocab_size)
