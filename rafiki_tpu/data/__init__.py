"""Dataset formats, loaders, synthetic generators, device prefetch."""

from .bpe import ByteBPETokenizer
from .dataset import (FASHION_CLASSES, CorpusDataset,
                      ImageClassificationDataset,
                      TabularDataset, TextClassificationDataset,
                      generate_corpus_dataset,
                      generate_fashion_archive,
                      generate_image_classification_dataset,
                      generate_tabular_dataset,
                      generate_text_classification_dataset,
                      load_image_classification_dataset,
                      load_tabular_dataset,
                      load_text_classification_dataset)
from .loader import batch_iterator, bucket_pad, prefetch_to_device

__all__ = [
    "ByteBPETokenizer", "FASHION_CLASSES",
    "CorpusDataset", "ImageClassificationDataset", "TabularDataset",
    "TextClassificationDataset", "generate_corpus_dataset",
    "generate_fashion_archive",
    "generate_image_classification_dataset", "generate_tabular_dataset",
    "generate_text_classification_dataset",
    "load_image_classification_dataset", "load_tabular_dataset",
    "load_text_classification_dataset", "batch_iterator", "bucket_pad",
    "prefetch_to_device",
]
