"""Dataset formats + loaders + synthetic generators.

Parity target: the reference's ``dataset_utils`` (SURVEY.md §2 "Dataset
utils"): image-classification archives and token/tag corpus files. TPU-first
deltas:

- The canonical on-disk image format is a single ``.npz`` with uint8
  ``images`` [N,H,W,C], int64 ``labels`` [N] and scalar ``n_classes`` —
  one mmap-able file instead of a zip of PNGs, so workers start trials
  without an unpack step. A directory-of-PNGs + ``labels.csv`` importer is
  provided for compatibility.
- Because this environment has zero egress, first-party *synthetic*
  generators stand in for FashionMNIST/ImageNet downloads: class-conditional
  structured images that are genuinely learnable, so advisor-convergence
  tests have signal, not noise.
"""

from __future__ import annotations

import csv
import io
import json
import zipfile
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# Image classification
# ---------------------------------------------------------------------------

@dataclass
class ImageClassificationDataset:
    images: np.ndarray   # uint8 [N, H, W, C]
    labels: np.ndarray   # int64 [N]
    n_classes: int
    class_names: Optional[List[str]] = None

    def __len__(self) -> int:
        return int(self.images.shape[0])

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return tuple(self.images.shape[1:])  # type: ignore[return-value]

    def save(self, path: str) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        kwargs: Dict[str, np.ndarray] = dict(
            images=self.images, labels=self.labels,
            n_classes=np.asarray(self.n_classes))
        if self.class_names is not None:
            kwargs["class_names"] = np.asarray(self.class_names)
        np.savez_compressed(p, **kwargs)

    @staticmethod
    def load(path: str) -> "ImageClassificationDataset":
        with np.load(path, allow_pickle=False) as z:
            images = z["images"]
            labels = z["labels"].astype(np.int64)
            n_classes = int(z["n_classes"])
            class_names = (list(map(str, z["class_names"]))
                           if "class_names" in z else None)
        if images.ndim == 3:  # grayscale without channel dim
            images = images[..., None]
        return ImageClassificationDataset(images, labels, n_classes,
                                          class_names)


def load_image_classification_dataset(path: str) -> ImageClassificationDataset:
    """Load any supported image-classification dataset layout.

    Supported: ``.npz`` canonical; ``.zip`` of images + ``labels.csv``
    (reference's archive format); directory with ``labels.csv``.
    """
    p = Path(path)
    if p.is_file() and p.suffix == ".npz":
        return ImageClassificationDataset.load(path)
    if p.is_file() and p.suffix == ".zip":
        return _load_zip_dataset(p)
    if p.is_dir() and (p / "labels.csv").exists():
        return _load_dir_dataset(p)
    raise ValueError(f"unrecognized image dataset at {path!r}")


def _read_labels_csv(fp) -> List[Tuple[str, str]]:
    rows = list(csv.reader(io.TextIOWrapper(fp) if hasattr(fp, "read1")
                           else fp))
    if rows and rows[0] and rows[0][0].strip().lower() in ("path", "image"):
        rows = rows[1:]
    return [(r[0].strip(), r[1].strip()) for r in rows if len(r) >= 2]


def _stack_images(pil_images) -> np.ndarray:
    arrs = [np.asarray(im) for im in pil_images]
    shape = arrs[0].shape
    if any(a.shape != shape for a in arrs):
        raise ValueError("all images in a dataset must share one shape")
    out = np.stack(arrs).astype(np.uint8)
    if out.ndim == 3:
        out = out[..., None]
    return out


def _labels_to_ids(names: Sequence[str]) -> Tuple[np.ndarray, List[str]]:
    classes = sorted(set(names))
    index = {c: i for i, c in enumerate(classes)}
    return np.asarray([index[n] for n in names], dtype=np.int64), classes


def _load_zip_dataset(p: Path) -> ImageClassificationDataset:
    from PIL import Image

    with zipfile.ZipFile(p) as z:
        with z.open("labels.csv") as f:
            pairs = _read_labels_csv(io.TextIOWrapper(f))
        images = [Image.open(io.BytesIO(z.read(rel))) for rel, _ in pairs]
    labels, classes = _labels_to_ids([lab for _, lab in pairs])
    return ImageClassificationDataset(_stack_images(images), labels,
                                      len(classes), classes)


def _load_dir_dataset(p: Path) -> ImageClassificationDataset:
    from PIL import Image

    with open(p / "labels.csv") as f:
        pairs = _read_labels_csv(f)
    images = []
    for rel, _ in pairs:  # eager load: bounded open-fd count
        with Image.open(p / rel) as im:
            images.append(np.asarray(im))
    labels, classes = _labels_to_ids([lab for _, lab in pairs])
    return ImageClassificationDataset(_stack_images(images), labels,
                                      len(classes), classes)


# ---------------------------------------------------------------------------
# Corpus (POS tagging)
# ---------------------------------------------------------------------------

@dataclass
class CorpusDataset:
    """Token/tag corpus: sentences of (token, tag) pairs.

    On-disk format (reference-compatible in spirit): a ``.jsonl`` where each
    line is ``{"tokens": [...], "tags": [...]}``, plus a ``meta`` first line
    with the tag vocabulary.
    """

    sentences: List[Tuple[List[str], List[str]]]
    tag_names: List[str]

    def __len__(self) -> int:
        return len(self.sentences)

    def save(self, path: str) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "w") as f:
            f.write(json.dumps({"tag_names": self.tag_names}) + "\n")
            for tokens, tags in self.sentences:
                f.write(json.dumps({"tokens": tokens, "tags": tags}) + "\n")

    @staticmethod
    def load(path: str) -> "CorpusDataset":
        with open(path) as f:
            meta = json.loads(f.readline())
            sentences = []
            for line in f:
                d = json.loads(line)
                if len(d["tokens"]) != len(d["tags"]):
                    raise ValueError("tokens/tags length mismatch")
                sentences.append((d["tokens"], d["tags"]))
        return CorpusDataset(sentences, meta["tag_names"])


# ---------------------------------------------------------------------------
# Tabular
# ---------------------------------------------------------------------------

@dataclass
class TabularDataset:
    """Feature-vector table (reference zoo: sklearn DT / xgboost tabular).

    Canonical on-disk form: ``.npz`` with float32 ``features`` [N, D],
    int64 ``labels`` [N] and scalar ``n_classes`` (0 ⇒ regression, labels
    float). A ``.csv`` importer (last column = label, header optional) is
    provided for reference-format compatibility.
    """

    features: np.ndarray  # float32 [N, D]
    labels: np.ndarray    # int64 [N] (classification) | float32 (regression)
    n_classes: int        # 0 for regression
    feature_names: Optional[List[str]] = None

    def __len__(self) -> int:
        return int(self.features.shape[0])

    def save(self, path: str) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        kwargs: Dict[str, np.ndarray] = dict(
            features=self.features.astype(np.float32), labels=self.labels,
            n_classes=np.asarray(self.n_classes))
        if self.feature_names is not None:
            kwargs["feature_names"] = np.asarray(self.feature_names)
        np.savez_compressed(p, **kwargs)

    @staticmethod
    def load(path: str) -> "TabularDataset":
        with np.load(path, allow_pickle=False) as z:
            feats = z["features"].astype(np.float32)
            n_classes = int(z["n_classes"])
            labels = (z["labels"].astype(np.int64) if n_classes
                      else z["labels"].astype(np.float32))
            names = (list(map(str, z["feature_names"]))
                     if "feature_names" in z else None)
        return TabularDataset(feats, labels, n_classes, names)


def load_tabular_dataset(path: str) -> TabularDataset:
    p = Path(path)
    if p.suffix == ".npz":
        return TabularDataset.load(path)
    if p.suffix == ".csv":
        return _load_csv_tabular(p)
    raise ValueError(f"unrecognized tabular dataset at {path!r}")


def _load_csv_tabular(p: Path) -> TabularDataset:
    with open(p) as f:
        rows = list(csv.reader(f))
    names: Optional[List[str]] = None
    try:
        float(rows[0][0])
    except (ValueError, IndexError):
        names, rows = rows[0][:-1], rows[1:]
    feats = np.asarray([[float(v) for v in r[:-1]] for r in rows],
                       np.float32)
    raw = [r[-1].strip() for r in rows]
    try:
        as_float = np.asarray([float(v) for v in raw])
        if np.allclose(as_float, np.round(as_float)):
            labels = as_float.astype(np.int64)
            return TabularDataset(feats, labels,
                                  int(labels.max()) + 1, names)
        return TabularDataset(feats, as_float.astype(np.float32), 0, names)
    except ValueError:  # string class labels
        labels, classes = _labels_to_ids(raw)
        return TabularDataset(feats, labels, len(classes), names)


# ---------------------------------------------------------------------------
# Text classification
# ---------------------------------------------------------------------------

@dataclass
class TextClassificationDataset:
    """Labeled text: ``.jsonl`` with a ``{"n_classes": N}`` meta first line
    then ``{"text": ..., "label": int}`` lines (the format
    :func:`generate_text_classification_dataset` emits)."""

    texts: List[str]
    labels: np.ndarray  # int64 [N]
    n_classes: int

    def __len__(self) -> int:
        return len(self.texts)

    @staticmethod
    def load(path: str) -> "TextClassificationDataset":
        texts: List[str] = []
        labels: List[int] = []
        with open(path) as f:
            meta = json.loads(f.readline())
            for line in f:
                d = json.loads(line)
                texts.append(str(d["text"]))
                labels.append(int(d["label"]))
        return TextClassificationDataset(
            texts, np.asarray(labels, np.int64), int(meta["n_classes"]))


def load_text_classification_dataset(path: str) -> TextClassificationDataset:
    return TextClassificationDataset.load(path)


# ---------------------------------------------------------------------------
# Synthetic generators (no-egress stand-ins for benchmark datasets)
# ---------------------------------------------------------------------------

def generate_image_classification_dataset(
        path: str, n_examples: int = 1024, image_size: int = 28,
        n_channels: int = 1, n_classes: int = 10, noise: float = 0.25,
        seed: int = 0, class_seed: int = 7) -> ImageClassificationDataset:
    """Learnable synthetic image dataset (FashionMNIST-shaped by default).

    Each class c gets a fixed random low-frequency template; examples are
    ``template[c] + noise``. Linear models reach good-but-imperfect accuracy,
    leaving headroom for knob search to matter.

    ``class_seed`` fixes the class templates independently of ``seed`` (which
    draws examples/noise), so train/val splits generated with different
    ``seed`` values share one underlying distribution.
    """
    rng = np.random.default_rng(seed)
    h = w = image_size
    # low-frequency templates: upsampled 7x7 random grids, fixed per class
    template_rng = np.random.default_rng(class_seed + n_classes * 1000
                                         + image_size)
    coarse = template_rng.normal(0.0, 1.0,
                                 size=(n_classes, 7, 7, n_channels))
    reps = int(np.ceil(h / 7))
    templates = np.repeat(np.repeat(coarse, reps, axis=1), reps, axis=2)
    templates = templates[:, :h, :w, :]
    labels = rng.integers(0, n_classes, size=n_examples).astype(np.int64)
    x = templates[labels] + rng.normal(0.0, noise * 2.0,
                                       size=(n_examples, h, w, n_channels))
    # fixed normalization bounds (templates ~ N(0,1) plus noise), so splits
    # generated with different `seed` values map to identical pixel scales
    bound = 3.0 + 3.0 * noise * 2.0
    x = np.clip((x + bound) / (2.0 * bound), 0.0, 1.0)
    images = (x * 255).astype(np.uint8)
    ds = ImageClassificationDataset(images, labels, n_classes,
                                    [f"class_{i}" for i in range(n_classes)])
    if path:
        ds.save(path)
    return ds


#: FashionMNIST's published class names — the fixture below writes them
#: into labels.csv so the archive reads like the real dataset's layout
FASHION_CLASSES = ["t_shirt_top", "trouser", "pullover", "dress", "coat",
                   "sandal", "shirt", "sneaker", "bag", "ankle_boot"]


def generate_fashion_archive(path: str, n_examples: int = 512,
                             seed: int = 0) -> ImageClassificationDataset:
    """FashionMNIST-LAYOUT zip fixture with synthetic content: 28x28
    grayscale PNG files under ``images/`` plus a ``labels.csv`` naming
    the published fashion classes — the REAL archive byte format the
    reference's quickstart downloads (SURVEY §4), generatable offline.

    The pixel content comes from the learnable synthetic generator
    (class templates + noise), so training outcomes carry signal; the
    FORMAT — PNG encoding, zip packaging, csv labels — is what the real
    FashionMNIST flow exercises and what the .npz generators skip.
    Round-trips through :func:`load_image_classification_dataset`'s
    zip loader. Returns the dataset for oracle use."""
    from PIL import Image

    if n_examples < len(FASHION_CLASSES):
        raise ValueError(
            f"n_examples={n_examples} cannot cover all "
            f"{len(FASHION_CLASSES)} fashion classes — the zip loader "
            "derives class ids from the classes PRESENT, so a missing "
            "class would silently misalign the returned oracle")
    s = seed
    while True:
        ds = generate_image_classification_dataset(
            "", n_examples=n_examples, image_size=28, n_channels=1,
            n_classes=len(FASHION_CLASSES), seed=s)
        # guarantee every class appears: the loader sorts the classes
        # it SEES, so full coverage is what keeps oracle label ids
        # aligned with loaded ones. Deterministic per (n, seed); a
        # re-draw is only ever taken at small n / unlucky seeds.
        if len(set(ds.labels.tolist())) == len(FASHION_CLASSES):
            break
        s += 1000003
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as z:
        rows = ["path,label"]
        for i in range(n_examples):
            im = Image.fromarray(ds.images[i, :, :, 0], mode="L")
            buf = io.BytesIO()
            im.save(buf, format="PNG")
            rel = f"images/{i:05d}.png"
            z.writestr(rel, buf.getvalue())
            rows.append(f"{rel},{FASHION_CLASSES[int(ds.labels[i])]}")
        z.writestr("labels.csv", "\n".join(rows) + "\n")
    # the zip loader sorts classes by NAME: re-map the oracle's labels
    # to that ordering so callers can compare predictions directly
    order = {c: i for i, c in enumerate(sorted(FASHION_CLASSES))}
    remapped = np.asarray([order[FASHION_CLASSES[int(l)]]
                           for l in ds.labels], np.int64)
    return ImageClassificationDataset(ds.images, remapped,
                                      len(FASHION_CLASSES),
                                      sorted(FASHION_CLASSES))


def generate_corpus_dataset(path: str, n_sentences: int = 400,
                            vocab_size: int = 200, n_tags: int = 8,
                            max_len: int = 12, seed: int = 0,
                            class_seed: int = 7) -> CorpusDataset:
    """Synthetic POS-style corpus: each word type has a dominant tag, with
    a first-order tag transition structure an HMM can exploit.

    ``class_seed`` fixes the language structure (word→tag lexicon, tag
    transitions) independently of ``seed`` so different splits share it.
    """
    if vocab_size < n_tags:
        raise ValueError("vocab_size must be >= n_tags")
    rng = np.random.default_rng(seed)
    struct_rng = np.random.default_rng(class_seed + vocab_size)
    word_tag = struct_rng.integers(0, n_tags, size=vocab_size)
    # guarantee every tag at least one word, keeping word→tag a function
    word_tag[:n_tags] = np.arange(n_tags)
    trans = struct_rng.dirichlet(np.ones(n_tags) * 0.3, size=n_tags)
    tag_names = [f"TAG{i}" for i in range(n_tags)]
    words_by_tag = [np.where(word_tag == t)[0] for t in range(n_tags)]
    sentences = []
    for _ in range(n_sentences):
        length = int(rng.integers(3, max_len + 1))
        tags: List[int] = []
        toks: List[str] = []
        t = int(rng.integers(0, n_tags))
        for _ in range(length):
            tags.append(t)
            w = int(rng.choice(words_by_tag[t]))
            toks.append(f"w{w}")
            t = int(rng.choice(n_tags, p=trans[t]))
        sentences.append((toks, [tag_names[i] for i in tags]))
    ds = CorpusDataset(sentences, tag_names)
    if path:
        ds.save(path)
    return ds


def generate_tabular_dataset(path: str, n_examples: int = 1024,
                             n_features: int = 16, n_classes: int = 3,
                             noise: float = 0.1, seed: int = 0,
                             class_seed: int = 7) -> TabularDataset:
    """Learnable synthetic table: labels come from a fixed random
    axis-aligned decision structure (depth-3 teacher tree) plus noise, so
    both tree learners and MLPs have signal and headroom.

    ``class_seed`` fixes the teacher independently of ``seed``.
    """
    rng = np.random.default_rng(seed)
    teacher_rng = np.random.default_rng(class_seed + n_features * 100)
    x = rng.normal(0.0, 1.0, size=(n_examples, n_features)).astype(
        np.float32)
    # teacher: 3 random feature thresholds → 8 leaves → class ids
    feat = teacher_rng.integers(0, n_features, size=3)
    thr = teacher_rng.normal(0.0, 0.5, size=3)
    leaf_class = teacher_rng.integers(0, n_classes, size=8)
    bits = ((x[:, feat] > thr).astype(np.int64) *
            np.asarray([4, 2, 1])).sum(axis=1)
    labels = leaf_class[bits]
    flip = rng.random(n_examples) < noise
    labels = np.where(flip, rng.integers(0, n_classes, size=n_examples),
                      labels).astype(np.int64)
    ds = TabularDataset(x, labels, n_classes,
                        [f"f{i}" for i in range(n_features)])
    if path:
        ds.save(path)
    return ds


def generate_text_classification_dataset(
        path: str, n_examples: int = 512, vocab_size: int = 500,
        n_classes: int = 4, max_len: int = 32, seed: int = 0,
        class_seed: int = 7) -> str:
    """Synthetic text classification: class-conditional unigram mixtures.

    Saved as ``.jsonl`` lines ``{"text": ..., "label": int}`` with a meta
    first line. Returns the path. ``class_seed`` fixes the class language
    models independently of ``seed`` so splits share one distribution.
    """
    rng = np.random.default_rng(seed)
    dist_rng = np.random.default_rng(class_seed + vocab_size)
    class_dists = dist_rng.dirichlet(np.ones(vocab_size) * 0.05,
                                     size=n_classes)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "w") as f:
        f.write(json.dumps({"n_classes": n_classes}) + "\n")
        for _ in range(n_examples):
            c = int(rng.integers(0, n_classes))
            length = int(rng.integers(5, max_len + 1))
            words = rng.choice(vocab_size, size=length, p=class_dists[c])
            text = " ".join(f"tok{w}" for w in words)
            f.write(json.dumps({"text": text, "label": c}) + "\n")
    return str(p)
