"""Minibatch iteration with TPU-friendly static shapes + device prefetch.

XLA compiles one executable per input shape, so every batch this loader
yields has exactly ``batch_size`` rows — the final partial batch is padded
and accompanied by a validity mask. ``prefetch_to_device`` overlaps host →
HBM transfer of batch k+1 with compute on batch k (double buffering).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, Optional, Sequence

import numpy as np


def batch_iterator(arrays: Dict[str, np.ndarray], batch_size: int,
                   shuffle: bool = True, seed: int = 0,
                   drop_remainder: bool = False,
                   epochs: Optional[int] = 1) -> Iterator[Dict[str, np.ndarray]]:
    """Yield dicts of equal-length batches with a ``mask`` of valid rows.

    All values in ``arrays`` must share leading dimension N. Every yielded
    batch has static leading dimension ``batch_size``; padding rows repeat
    row 0 and are masked out.
    """
    n = len(next(iter(arrays.values())))
    for a in arrays.values():
        if len(a) != n:
            raise ValueError("all arrays must share leading dimension")
    rng = np.random.default_rng(seed)
    epoch_iter = itertools.count() if epochs is None else range(epochs)
    for _ in epoch_iter:
        idx = rng.permutation(n) if shuffle else np.arange(n)
        for start in range(0, n, batch_size):
            take = idx[start:start + batch_size]
            if len(take) < batch_size:
                if drop_remainder:
                    break
                pad = np.zeros(batch_size - len(take), dtype=take.dtype)
                mask = np.concatenate([np.ones(len(take), dtype=bool),
                                       np.zeros(batch_size - len(take),
                                                dtype=bool)])
                take = np.concatenate([take, pad])
            else:
                mask = np.ones(batch_size, dtype=bool)
            out = {k: v[take] for k, v in arrays.items()}
            out["mask"] = mask
            yield out


def prefetch_to_device(iterator: Iterator[Any], size: int = 2,
                       devices: Optional[Sequence[Any]] = None,
                       sharding: Optional[Any] = None) -> Iterator[Any]:
    """Double-buffer host batches onto device ahead of compute.

    With ``sharding`` given (e.g. a batch-axis ``NamedSharding``), every
    leaf of the batch pytree is placed with it — the template train loops
    use this so host→HBM transfer of batch k+1 overlaps the compiled
    step on batch k. With ``devices``, placement is on the first device
    (single-device fast path). With neither, the default device.
    """
    import collections

    import jax

    queue: "collections.deque[Any]" = collections.deque()
    device = devices[0] if devices else None

    def _put(batch: Any) -> Any:
        if sharding is not None:
            return jax.device_put(batch, sharding)
        if device is not None:
            return jax.device_put(batch, device)
        return jax.device_put(batch)

    for batch in iterator:
        queue.append(_put(batch))
        if len(queue) >= size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


def bucket_pad(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= length (serving-side shape bucketing); the largest
    bucket is returned for oversize inputs (caller truncates)."""
    for b in sorted(buckets):
        if length <= b:
            return b
    return max(buckets)
