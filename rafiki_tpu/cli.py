"""``rafiki-tpu`` command-line entry point.

Replaces the reference's ``scripts/start.sh``/``stop.sh`` + per-service
Docker entrypoints (SURVEY.md §2 "Deployment") with one multi-command CLI.
Service subcommands are registered as their layers land.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rafiki-tpu",
        description="TPU-native AutoML train-and-serve framework")
    sub = parser.add_subparsers(dest="cmd")

    sub.add_parser("version", help="print version")

    p_tune = sub.add_parser(
        "tune", help="local tuning loop over a zoo template (dev use)")
    p_tune.add_argument("template", help="zoo template name, e.g. JaxFeedForward")
    p_tune.add_argument("train_dataset")
    p_tune.add_argument("val_dataset")
    p_tune.add_argument("--trials", type=int, default=5)
    p_tune.add_argument("--advisor", default="auto")
    p_tune.add_argument("--profile", metavar="DIR", default=None,
                        help="write a jax.profiler trace per trial to DIR")

    p_bpe = sub.add_parser(
        "bpe-train",
        help="train a byte-level BPE tokenizer artifact from a corpus "
             "(for LlamaLoRA's tokenizer_path knob)")
    p_bpe.add_argument("corpus", help="UTF-8 text file (or .jsonl with "
                                      "'text' fields) to learn merges from")
    p_bpe.add_argument("out", help="artifact path, e.g. bpe.json")
    p_bpe.add_argument("--vocab", type=int, default=8192,
                       help="target vocab size (specials + 256 bytes + "
                            "merges)")

    p_doc = sub.add_parser(
        "doctor",
        help="check the environment (backend, devices, native "
             "artifacts, compile cache) and print a health report; "
             "with --workdir, audit a stack workdir instead (MetaStore "
             "rows vs live pids vs slots vs obs ports — drift report)")
    p_doc.add_argument("--workdir", default=None,
                       help="stack workdir to audit (read-only; safe "
                            "against a live stack)")
    p_doc.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the audit as JSON (with --workdir)")

    p_backup = sub.add_parser(
        "backup",
        help="snapshot a stack's MetaStore (SQLite online backup; "
             "consistent under a live admin) — run before risky ops")
    p_backup.add_argument("out", help="destination file for the snapshot")
    p_backup.add_argument("--workdir", default="./rafiki_stack",
                          help="stack workdir holding meta.db")

    p_lint = sub.add_parser(
        "lint",
        help="run the JAX/concurrency-aware static analyzer over "
             "source paths (exit 0 = clean; see docs/linting.md)")
    from .analysis.cli import add_lint_arguments

    add_lint_arguments(p_lint)

    _register_service_commands(sub)

    args = parser.parse_args(argv)
    if args.cmd is None:
        parser.print_help()
        return 2
    if args.cmd == "lint":
        # pure AST analysis — no jax, no backend, no platform env;
        # keeping it import-light makes the CI gate start instantly
        from .analysis.cli import run_lint

        return run_lint(args)
    if args.cmd == "doctor" and args.workdir:
        # workdir drift audit: pure /proc + sqlite reads, no jax, no
        # backend — must work on a box whose accelerator is wedged
        # (that is exactly when operators reach for it)
        return _doctor_workdir(args.workdir, args.as_json)
    if args.cmd == "backup":
        import json as _json

        from .store.meta_store import MetaStore

        db = f"{args.workdir}/meta.db"
        import os.path

        if not os.path.exists(db):
            print(f"no MetaStore at {db}", file=sys.stderr)
            return 1
        # read-only open: the backup tool must never migrate or touch
        # the live store it is snapshotting
        out = MetaStore(db, read_only=True).backup(args.out)
        print(_json.dumps({"ok": True, **out}))
        return 0
    # honor RAFIKI_JAX_PLATFORM before any backend initializes: the TPU-VM
    # image pre-imports jax with the accelerator platform pinned, so env
    # vars alone cannot force dev/tune runs onto CPU
    from .utils.platform import apply_platform_env

    apply_platform_env()
    if args.cmd == "version":
        from . import __version__

        print(__version__)
        return 0
    if args.cmd == "tune":
        from .model import tune_model
        from .models import get_model_template

        result = tune_model(get_model_template(args.template),
                            args.train_dataset, args.val_dataset,
                            total_trials=args.trials,
                            advisor_type=args.advisor,
                            profile_dir=args.profile)
        print(f"best_score={result.best_score:.4f} "
              f"best_knobs={result.best_knobs}")
        return 0
    if args.cmd == "bpe-train":
        import json

        from .data.bpe import ByteBPETokenizer

        is_jsonl = args.corpus.endswith(".jsonl")

        def lines():
            # format by EXTENSION, not per-line sniffing: a plain-text
            # corpus may legitimately contain JSON-looking lines, and a
            # .jsonl metadata row must not leak '{"'-style punctuation
            # into the merge table
            with open(args.corpus, encoding="utf-8") as f:
                for line in f:
                    if not is_jsonl:
                        yield line
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    text = rec.get("text") if isinstance(rec, dict) \
                        else None
                    if isinstance(text, str):  # skip metadata/null rows
                        yield text

        tok = ByteBPETokenizer.train(lines(), vocab_size=args.vocab)
        tok.save(args.out)
        print(f"vocab_size={tok.vocab_size} merges={len(tok.merges)} "
              f"-> {args.out}")
        return 0
    if args.cmd == "doctor":
        return _doctor()
    return _run_service_command(args)


def _doctor_workdir(workdir: str, as_json: bool) -> int:
    """Drift audit over a stack workdir; exit 0 iff zero drift."""
    import json as _json

    from .admin.doctor import audit_workdir, render_text

    report = audit_workdir(workdir)
    if as_json:
        print(_json.dumps(report, indent=2))
    else:
        print(render_text(report))
    return 0 if report["ok"] else 1


def _doctor() -> int:
    """Operator health report: every row is a check with a pass/fail
    mark; exit 0 iff all load-bearing checks pass. Never claims the
    accelerator beyond a tiny matmul (a doctor must not wedge on a
    flaky tunnel longer than one probe)."""
    ok = True

    def row(good: bool, label: str, detail: str = "",
            fatal: bool = True) -> None:
        nonlocal ok
        mark = "ok " if good else ("FAIL" if fatal else "warn")
        print(f"[{mark}] {label}" + (f": {detail}" if detail else ""))
        if fatal and not good:
            ok = False

    from . import __version__

    row(True, "rafiki-tpu", __version__)
    try:
        import jax

        backend = jax.default_backend()
        devs = jax.devices()
        row(True, "jax backend", f"{backend}, {len(devs)} device(s)")
        import time

        import jax.numpy as jnp

        t0 = time.perf_counter()
        x = jnp.ones((256, 256), jnp.bfloat16)
        (x @ x).block_until_ready()
        row(True, "device matmul",
            f"bf16 256x256 in {time.perf_counter() - t0:.2f}s "
            "(first call includes compile)")
    except Exception as e:  # noqa: BLE001 — the report IS the product
        row(False, "jax backend", str(e))
    try:
        from .native.client import ensure_built

        row(True, "native kv server", str(ensure_built()))
        row(True, "native bpe encoder",
            str(ensure_built(target="librbpe.so")))
    except Exception as e:  # noqa: BLE001
        row(False, "native build", str(e), fatal=False)
    try:
        from .data.bpe import ByteBPETokenizer

        tok = ByteBPETokenizer.train(["doctor check"] * 4,
                                     vocab_size=270)
        row(tok.decode(tok.encode_ids("doctor")) == "doctor",
            "bpe round-trip",
            "native" if tok._native is not None else "python fallback")
    except Exception as e:  # noqa: BLE001
        row(False, "bpe round-trip", str(e))
    import os

    from .utils.platform import CACHE_ENV, compile_cache_path

    path = compile_cache_path()
    if path is None:
        row(True, "compile cache", f"disabled by {CACHE_ENV}")
    else:
        # the dir (and its parents, e.g. ~/.cache/rafiki_tpu on a fresh
        # host) may not exist yet — apply_platform_env's makedirs will
        # create the whole chain, so test W_OK at the nearest EXISTING
        # ancestor rather than warning spuriously
        probe = path  # start at the path ITSELF: it may be a plain file
        blocked = False  # a FILE at any level blocks makedirs
        while probe and not os.path.isdir(probe):
            if os.path.exists(probe):
                blocked = True
                break
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
        row(not blocked and os.access(probe or ".", os.W_OK),
            "compile cache", path, fatal=False)
    pg = os.environ.get("RAFIKI_PG_URL", "")
    if not pg:
        row(True, "postgres",
            "not configured (RAFIKI_PG_URL unset; sqlite is the default "
            "MetaStore backing)")
    else:
        from urllib.parse import urlsplit

        def redact(text: str) -> str:
            # structural redaction (not a regex over the URL — an
            # unencoded '@' or '/' inside a password defeats those):
            # every userinfo fragment is scrubbed from any output,
            # including driver exception text that may echo the URL
            try:
                netloc = urlsplit(pg).netloc
            except ValueError:
                netloc = ""
            userinfo, _, _hostport = netloc.rpartition("@")
            if userinfo:
                text = text.replace(userinfo, "***")
                pw = userinfo.partition(":")[2]
                if pw:
                    text = text.replace(pw, "***")
            return text

        shown = redact(pg)
        try:
            from .store.db import PostgresAdapter

            a = PostgresAdapter(pg)
            conn = a.connect()
            try:
                one = a.execute(conn, "SELECT 1 AS ok").fetchone()
            finally:
                a.close(conn)
            row(bool(one and one.get("ok") == 1), "postgres", shown,
                fatal=False)
        except Exception as e:  # noqa: BLE001 — the report IS the product
            row(False, "postgres", redact(f"{shown}: {e}"), fatal=False)
    print("all checks passed" if ok else "SOME CHECKS FAILED")
    return 0 if ok else 1


def _register_service_commands(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("stack", help="manage the full local service stack")
    p.add_argument("action", choices=["start", "stop", "status"])
    p.add_argument("--workdir", default="./rafiki_stack")
    p.add_argument("--port", type=int, default=3000,
                   help="admin REST port")
    p.add_argument("--workers", type=int, default=1,
                   help="train workers per job when the budget names no "
                        "WORKER_COUNT/GPU_COUNT")
    p.add_argument("--slot-size", dest="slot_size", type=int, default=1,
                   help="devices per trial slot (ICI-contiguous sub-mesh "
                        "size; e.g. 2 on 8 devices -> 4 slots)")
    p.add_argument("--cold", action="store_true",
                   help="start: kill every recorded survivor instead of "
                        "re-adopting it (clean-slate boot for when the "
                        "previous stack's state is not to be trusted)")


def _run_service_command(args: argparse.Namespace) -> int:
    if args.cmd == "stack":
        try:
            from .admin.stack import stack_command
        except ImportError:
            print("the service stack is not available in this build",
                  file=sys.stderr)
            return 2
        return stack_command(args)
    print(f"unknown command {args.cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
