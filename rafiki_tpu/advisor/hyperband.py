"""BOHB-family advisor: asynchronous successive halving + TPE sampling.

Parity target: the reference's HyperBand/BOHB-family advisor (SURVEY.md §2
"Advisor service", BASELINE.json "Bayesian/BOHB"). Rebuilt as the *async*
variant (ASHA-style promotion) because trials here are long-running
processes on TPU sub-meshes: a synchronous rung barrier would idle
sub-meshes waiting for stragglers, while async promotion keeps every
sub-mesh busy — the same reasoning that moved the field from HyperBand to
ASHA. New configurations are drawn from a TPE-style model (top-quantile vs
rest KDEs over the knob unit cube) once enough full-rung observations
exist, which is the "BO" in BOHB.

Budget semantics: a proposal's ``budget_scale`` is the fraction of the
model's full training budget (e.g. epochs) to spend. A promoted trial
warm-starts from its own lower-rung checkpoint via
``warm_start_trial_id`` — which maps BOHB rungs directly onto the
ParamStore's share/resume machinery (SURVEY.md §5.3/§5.4: rungs pair
naturally with checkpointed, preemptible trials).

Gang/batched use: the base class's atomic ``propose_batch`` /
``feedback_batch`` drive the same ``_propose``/``_feedback`` hooks, so
per-lane rung state (``_by_trial_no``) is registered for every batch
member before any lane trains and promotion decisions are identical to
the sequential call sequence. ASHA's async promotion rule is what makes
in-place lane culling sound: a lane's trial finishes its rung, the
batch feedback lands, and the very next ``propose_batch`` may hand back
a promotion of that trial (same knobs, higher budget, warm start) —
which the gang engine maps onto "keep the lane's params, reset its
optimizer" with no recompile.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..model.knob import (KnobConfig, PolicyKnob, knobs_from_unit_vector,
                          knobs_to_unit_vector, sample_knobs, tunable_knobs)
from .base import BaseAdvisor, Proposal, TrialResult


class _RungEntry:
    __slots__ = ("trial_no", "trial_id", "knobs", "vec", "score", "promoted")

    def __init__(self, trial_no: int, knobs: dict, vec: List[float]) -> None:
        self.trial_no = trial_no
        self.trial_id = ""
        self.knobs = knobs
        self.vec = vec
        self.score: Optional[float] = None
        self.promoted = False


class BOHBAdvisor(BaseAdvisor):
    name = "bohb"

    def __init__(self, knob_config: KnobConfig,
                 total_trials: Optional[int] = None,
                 time_budget_s: Optional[float] = None, seed: int = 0,
                 eta: int = 3, min_budget: float = 1.0 / 9.0,
                 max_budget: float = 1.0, tpe_min_points: int = 8,
                 tpe_top_quantile: float = 0.33,
                 n_candidates: int = 256) -> None:
        super().__init__(knob_config, total_trials, time_budget_s, seed)
        self.eta = eta
        # rung budgets: min_budget * eta^k up to max_budget
        budgets = []
        b = min_budget
        while b < max_budget - 1e-9:
            budgets.append(b)
            b *= eta
        budgets.append(max_budget)
        self.budgets = budgets
        self._rungs: List[List[_RungEntry]] = [[] for _ in budgets]
        self._by_trial_no: Dict[int, Tuple[int, _RungEntry]] = {}
        self._dims = tunable_knobs(knob_config)
        self._tpe_min_points = tpe_min_points
        self._tpe_top_quantile = tpe_top_quantile
        self._n_candidates = n_candidates
        self._np_rng = np.random.default_rng(seed)

    @property
    def n_rungs(self) -> int:
        return len(self.budgets)

    # ---- BaseAdvisor hooks (called under the base lock) ----
    def _propose(self, trial_no: int) -> Proposal:
        # 0) final-trial reservation: with a small trial budget the ASHA
        # rungs may never organically reach full budget (promotion needs
        # >= eta completions per rung), which would leave the job with no
        # full-budget trial at all. Spend the last trial running the
        # incumbent at budget 1.0 so a best trial always exists.
        if (self.total_trials is not None
                and self.total_trials - trial_no <= 1
                and not any(r.budget_scale >= 1.0 for r in self.results)
                and not any(p.budget_scale >= 1.0
                            for p in self._outstanding.values())):
            return self._final_fill(trial_no)
        # 1) try to promote: highest rung first, so survivors finish fast
        for rung in range(self.n_rungs - 2, -1, -1):
            entry = self._promotable(rung)
            if entry is not None:
                entry.promoted = True
                new = _RungEntry(trial_no, dict(entry.knobs), entry.vec)
                self._rungs[rung + 1].append(new)
                self._by_trial_no[trial_no] = (rung + 1, new)
                knobs = self._with_policies(
                    dict(entry.knobs), promote=True,
                    budget_scale=self.budgets[rung + 1])
                return Proposal(
                    trial_no=trial_no, knobs=knobs,
                    budget_scale=self.budgets[rung + 1],
                    warm_start_trial_id=entry.trial_id,
                    meta={"rung": rung + 1, "parent_trial_no": entry.trial_no})
        # 2) otherwise: a fresh configuration at the lowest rung
        return self._fresh_entry(trial_no, rung=0)

    def _fresh_entry(self, trial_no: int, rung: int,
                     final_fill: bool = False) -> Proposal:
        """Sample a fresh configuration and register it at ``rung``."""
        if self._dims:
            vec = self._sample_tpe()
            knobs = knobs_from_unit_vector(self.knob_config, vec, self._rng)
        else:
            knobs = sample_knobs(self.knob_config, self._rng)
            vec = []
        entry = _RungEntry(trial_no, dict(knobs), vec)
        self._rungs[rung].append(entry)
        self._by_trial_no[trial_no] = (rung, entry)
        knobs = self._with_policies(knobs, promote=False,
                                    budget_scale=self.budgets[rung])
        meta = {"rung": rung}
        if final_fill:
            meta["final_fill"] = True
        return Proposal(trial_no=trial_no, knobs=knobs,
                        budget_scale=self.budgets[rung], meta=meta)

    def _final_fill(self, trial_no: int) -> Proposal:
        """Run the best completed entry (highest rung, then score) at full
        budget, warm-started from its checkpoint; fresh sample if nothing
        has completed yet."""
        top = self.n_rungs - 1
        best = None
        for rung in range(self.n_rungs - 1, -1, -1):
            done = [e for e in self._rungs[rung] if e.score is not None]
            if done:
                best = max(done, key=lambda e: e.score)
                break
        if best is not None:
            entry = _RungEntry(trial_no, dict(best.knobs), best.vec)
            self._rungs[top].append(entry)
            self._by_trial_no[trial_no] = (top, entry)
            knobs = self._with_policies(dict(best.knobs), promote=True,
                                        budget_scale=1.0)
            return Proposal(
                trial_no=trial_no, knobs=knobs, budget_scale=1.0,
                warm_start_trial_id=best.trial_id,
                meta={"rung": top, "parent_trial_no": best.trial_no,
                      "final_fill": True})
        return self._fresh_entry(trial_no, rung=top, final_fill=True)

    #: per-rung history cap for long-running services: beyond this, the
    #: worst-scoring unpromoted entries are pruned (they are strictly
    #: dominated, so dropping them only tightens the promotion bar).
    MAX_RUNG_ENTRIES = 2048

    def _feedback(self, result: TrialResult) -> None:
        info = self._by_trial_no.pop(result.trial_no, None)
        if info is None:
            return
        rung, entry = info
        entry.score = float(result.score)
        entry.trial_id = result.trial_id
        if len(self._rungs[rung]) > self.MAX_RUNG_ENTRIES:
            done = sorted((e for e in self._rungs[rung]
                           if e.score is not None and not e.promoted),
                          key=lambda e: e.score)
            drop = set(id(e) for e in
                       done[:len(self._rungs[rung]) - self.MAX_RUNG_ENTRIES])
            self._rungs[rung] = [e for e in self._rungs[rung]
                                 if id(e) not in drop]

    def _on_trial_errored(self, trial_no: int) -> None:
        info = self._by_trial_no.pop(trial_no, None)
        if info is not None:
            rung, entry = info
            # drop it from the rung so it never blocks promotions
            self._rungs[rung] = [e for e in self._rungs[rung] if e is not entry]

    # ---- successive halving ----
    def _promotable(self, rung: int) -> Optional[_RungEntry]:
        """Async (ASHA) rule: an entry is promotable when it sits in the top
        1/eta of *completed* entries at its rung and is not yet promoted."""
        done = [e for e in self._rungs[rung] if e.score is not None]
        if len(done) < self.eta:
            return None
        k = len(done) // self.eta
        top = sorted(done, key=lambda e: e.score, reverse=True)[:k]
        for e in top:
            if not e.promoted:
                return e
        return None

    def _with_policies(self, knobs: dict, promote: bool,
                       budget_scale: float) -> dict:
        """Flip the model's declared policy knobs for rung semantics.

        QUICK_TRAIN only on sub-full rungs: a full-budget (scale 1.0)
        trial must actually train at full budget, or rung budgets become
        indistinguishable for models whose quick_train caps epochs."""
        for n, k in self.knob_config.items():
            if not isinstance(k, PolicyKnob):
                continue
            if k.policy == "QUICK_TRAIN":
                knobs[n] = budget_scale < 1.0 - 1e-9
            elif k.policy == "EARLY_STOP":
                knobs[n] = True
            elif k.policy == "SHARE_PARAMS":
                knobs[n] = promote  # promotions resume their own checkpoint
        return knobs

    # ---- TPE sampling over the unit cube ----
    def _observations(self) -> Tuple[np.ndarray, np.ndarray]:
        """(x, y) pairs from the highest rung that has enough data."""
        for rung in range(self.n_rungs - 1, -1, -1):
            done = [e for e in self._rungs[rung]
                    if e.score is not None and e.vec]
            if len(done) >= self._tpe_min_points:
                return (np.asarray([e.vec for e in done]),
                        np.asarray([e.score for e in done]))
        return np.empty((0, len(self._dims))), np.empty((0,))

    def _sample_tpe(self) -> List[float]:
        x, y = self._observations()
        if len(y) < self._tpe_min_points:
            return self._np_rng.random(len(self._dims)).tolist()
        from scipy.stats import gaussian_kde

        n_top = max(2, int(math.ceil(len(y) * self._tpe_top_quantile)))
        if n_top <= x.shape[1]:
            # a KDE over fewer points than dimensions has a singular
            # covariance — scipy raises outright (surfaced by the gang
            # engine's batched pulls on the 4-dim MLP space); keep
            # exploring randomly until the top quantile outgrows the
            # dimensionality
            return self._np_rng.random(len(self._dims)).tolist()
        order = np.argsort(y)[::-1]
        good, bad = x[order[:n_top]], x[order[n_top:]]
        jitter = 1e-3 * self._np_rng.standard_normal(good.T.shape)
        try:
            kde_good = gaussian_kde(good.T + jitter, bw_method="scott")
            kde_bad = (gaussian_kde(bad.T, bw_method="scott")
                       if len(bad) > x.shape[1] else None)
        except (np.linalg.LinAlgError, ValueError):
            return self._np_rng.random(len(self._dims)).tolist()
        cand = np.clip(
            kde_good.resample(self._n_candidates,
                              seed=int(self._np_rng.integers(2 ** 31))).T,
            0.0, 1.0)
        lg = kde_good.logpdf(cand.T)
        lb = kde_bad.logpdf(cand.T) if kde_bad is not None else 0.0
        return cand[int(np.argmax(lg - lb))].tolist()
