"""Architecture-search advisor: regularized evolution with param sharing.

Parity target: the late-upstream reference's ENAS-style architecture
search (SURVEY.md §2 "Advisor service"). The TPU-first re-design uses
aging (regularized) evolution over the template's ``shape_relevant``
knobs instead of an RL controller — same search behavior class, no
recurrent controller to train, and it composes with this framework's
two native affordances:

- **Parameter sharing (the "ENAS" part):** a mutation that touches only
  non-shape knobs keeps the child's ``shape_signature`` equal to its
  parent's, so the proposal warm-starts from the parent's checkpoint
  (``warm_start_trial_id`` + SHARE_PARAMS policy). Weights flow along
  the lineage exactly like ENAS's shared supernet weights, but through
  the ParamStore the framework already has.
- **Compile-cache affinity:** children that keep the parent's shape
  signature also reuse its XLA executable (workers cache by
  ``shape_signature``), so the search spends chips on math, not
  recompiles.

Algorithm (Real et al., "Regularized Evolution for Image Classifier
Architecture Search", AAAI 2019 — public method, reimplemented):
seed ``population`` random configs; afterwards each proposal is a
mutation of the winner of a ``sample_size`` tournament drawn from the
most recent ``population`` results (aging: old individuals fall out of
the window, which is what regularizes).
"""

from __future__ import annotations

import collections
from typing import Deque, Optional

from ..model.knob import (PolicyKnob, sample_knobs, shape_signature,
                          tunable_knobs)
from .base import BaseAdvisor, Proposal, TrialResult


class ArchEvolutionAdvisor(BaseAdvisor):
    name = "arch_evo"

    def __init__(self, *args, population: int = 8, sample_size: int = 3,
                 **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self.population = max(2, int(population))
        self.sample_size = max(1, int(sample_size))
        #: aging window — only the newest ``population`` results compete
        self._window: Deque[TrialResult] = collections.deque(
            maxlen=self.population)

    # ---- BaseAdvisor hooks (called under the base lock) ----
    def _propose(self, trial_no: int) -> Proposal:
        if len(self._window) < self.population:
            return Proposal(trial_no=trial_no,
                            knobs=self._with_policies(
                                sample_knobs(self.knob_config, self._rng)))
        parent = max(self._rng.sample(list(self._window),
                                      min(self.sample_size,
                                          len(self._window))),
                     key=lambda r: r.score)
        child = dict(parent.knobs)
        mutated = self._mutate(child)
        child = self._with_policies(child)
        warm = ""
        if parent.trial_id and not self._changes_shape(parent.knobs,
                                                       child, mutated):
            # ENAS-style weight inheritance: same shapes → same pytree
            warm = parent.trial_id
        return Proposal(trial_no=trial_no, knobs=child,
                        warm_start_trial_id=warm,
                        meta={"parent_trial_no": parent.trial_no,
                              "mutated": mutated})

    def _feedback(self, result: TrialResult) -> None:
        self._window.append(result)

    # ---- internals ----
    def _mutate(self, knobs: dict) -> str:
        """Resample ONE tunable knob in place; returns its name."""
        names = tunable_knobs(self.knob_config)
        if not names:
            return ""
        for _ in range(8):  # retry until the value actually changes
            name = self._rng.choice(names)
            new = self.knob_config[name].sample(self._rng)
            if new != knobs.get(name):
                knobs[name] = new
                return name
        knobs[name] = self.knob_config[name].sample(self._rng)
        return name

    def _changes_shape(self, parent_knobs: dict, child_knobs: dict,
                       mutated: str) -> bool:
        if mutated and not getattr(self.knob_config.get(mutated),
                                   "shape_relevant", False):
            return False
        return shape_signature(self.knob_config, parent_knobs) != \
            shape_signature(self.knob_config, child_knobs)

    def _with_policies(self, knobs: dict) -> dict:
        """Policy knobs: enable SHARE_PARAMS so warm starts take effect;
        leave other policies at their sampled values."""
        for n, k in self.knob_config.items():
            if isinstance(k, PolicyKnob) and k.policy == "SHARE_PARAMS":
                knobs[n] = True
        return knobs
