"""Advisor as an HTTP service + client.

Parity target: the reference's advisor container serving propose/feedback
over HTTP to train workers (SURVEY.md §3.4). One advisor service hosts the
search state for one sub-train-job; the train worker's loop calls
``propose`` / ``feedback`` / ``trial_errored`` and polls ``status``.
"""

from __future__ import annotations

import argparse
from typing import Any, Dict, Optional, Tuple

from ..model.knob import knob_config_from_json
from ..utils.http import JsonHttpService, json_request
from .base import BaseAdvisor, Proposal, TrialResult, make_advisor


class AdvisorService:
    """Wraps a BaseAdvisor behind the propose/feedback wire protocol."""

    def __init__(self, advisor: BaseAdvisor, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.advisor = advisor
        self.http = JsonHttpService(host, port)
        self.http.route("POST", "/proposal", self._propose)
        self.http.route("POST", "/proposal_batch", self._propose_batch)
        self.http.route("POST", "/feedback", self._feedback)
        self.http.route("POST", "/feedback_batch", self._feedback_batch)
        self.http.route("POST", "/trial_errored", self._trial_errored)
        self.http.route("GET", "/status", self._status)

    def start(self) -> Tuple[str, int]:
        return self.http.start()

    def stop(self) -> None:
        self.http.stop()

    # ---- routes ----
    def _propose(self, _m: Dict[str, str], _body: Any,
                 _h: Dict[str, str]) -> Tuple[int, Any]:
        return 200, self.advisor.propose().to_json()

    def _propose_batch(self, _m: Dict[str, str], body: Any,
                       _h: Dict[str, str]) -> Tuple[int, Any]:
        # one advisor-side lock acquisition: the batch is atomic even
        # with multiple gang workers hitting the same service
        batch = self.advisor.propose_batch(int(body.get("k", 1)))
        return 200, {"proposals": [p.to_json() for p in batch]}

    def _feedback(self, _m: Dict[str, str], body: Any,
                  _h: Dict[str, str]) -> Tuple[int, Any]:
        self.advisor.feedback(TrialResult.from_json(body))
        return 200, {"ok": True}

    def _feedback_batch(self, _m: Dict[str, str], body: Any,
                        _h: Dict[str, str]) -> Tuple[int, Any]:
        self.advisor.feedback_batch(
            [TrialResult.from_json(r) for r in body.get("results", [])])
        return 200, {"ok": True}

    def _trial_errored(self, _m: Dict[str, str], body: Any,
                       _h: Dict[str, str]) -> Tuple[int, Any]:
        self.advisor.trial_errored(int(body["trial_no"]))
        return 200, {"ok": True}

    def _status(self, _m: Dict[str, str], _body: Any,
                _h: Dict[str, str]) -> Tuple[int, Any]:
        best = self.advisor.best
        return 200, {
            "finished": self.advisor.finished,
            "n_results": len(self.advisor.results),
            "best": best.to_json() if best else None,
        }


class AdvisorClient:
    """HTTP client mirroring the BaseAdvisor surface for remote workers."""

    def __init__(self, base_url: str, timeout: float = 60.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def propose(self) -> Proposal:
        return Proposal.from_json(json_request(
            "POST", f"{self.base_url}/proposal", {}, timeout=self.timeout))

    def propose_batch(self, k: int) -> list:
        body = json_request("POST", f"{self.base_url}/proposal_batch",
                            {"k": k}, timeout=self.timeout)
        return [Proposal.from_json(p) for p in body.get("proposals", [])]

    def feedback(self, result: TrialResult) -> None:
        json_request("POST", f"{self.base_url}/feedback", result.to_json(),
                     timeout=self.timeout)

    def feedback_batch(self, results: list) -> None:
        json_request("POST", f"{self.base_url}/feedback_batch",
                     {"results": [r.to_json() for r in results]},
                     timeout=self.timeout)

    def trial_errored(self, trial_no: int) -> None:
        json_request("POST", f"{self.base_url}/trial_errored",
                     {"trial_no": trial_no}, timeout=self.timeout)

    def status(self) -> Dict[str, Any]:
        return json_request("GET", f"{self.base_url}/status",
                            timeout=self.timeout)


def main(argv: Optional[list] = None) -> int:
    """Service entrypoint: ``python -m rafiki_tpu.advisor.service``.

    The ServicesManager spawns this with the knob config and budget as a
    JSON file path (env-var-sized configs don't survive exec portably).
    """
    import json as _json

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True,
                        help="path to JSON {knob_config, advisor_type, "
                             "total_trials, time_budget_s, seed}")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default="",
                        help="write the bound port here (service discovery)")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        cfg = _json.load(f)
    port_file = args.port_file or cfg.get("port_file", "")
    advisor = make_advisor(
        knob_config_from_json(cfg["knob_config"]),
        cfg.get("advisor_type", "auto"),
        total_trials=cfg.get("total_trials"),
        time_budget_s=cfg.get("time_budget_s"),
        seed=cfg.get("seed", 0))
    service = AdvisorService(advisor, args.host, args.port)
    host, port = service.start()
    if port_file:
        with open(port_file, "w") as f:
            f.write(str(port))
    print(f"advisor service on {host}:{port}", flush=True)
    service.http.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
