"""Bayesian-optimization advisor: GP surrogate + Expected Improvement.

Parity target: the reference's skopt-GP Bayesian advisor (SURVEY.md §2
"Advisor service"). skopt is not in this image, so the surrogate is built
directly on scikit-learn's GaussianProcessRegressor (Matérn 5/2 kernel)
over the knob unit cube (see ``knob.knobs_to_unit_vector``), with EI
maximized by candidate sampling. Pending proposals are imputed at the
posterior mean ("constant liar") so concurrent workers don't collapse onto
one point.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..model.knob import (KnobConfig, PolicyKnob, knobs_from_unit_vector,
                          knobs_to_unit_vector, sample_knobs,
                          shape_signature, tunable_knobs)
from .base import BaseAdvisor, Proposal, TrialResult


class BayesOptAdvisor(BaseAdvisor):
    name = "bayes_gp"

    def __init__(self, knob_config: KnobConfig,
                 total_trials: Optional[int] = None,
                 time_budget_s: Optional[float] = None, seed: int = 0,
                 n_initial_points: int = 8, n_candidates: int = 512,
                 xi: float = 0.01) -> None:
        super().__init__(knob_config, total_trials, time_budget_s, seed)
        self._dims = tunable_knobs(knob_config)
        self._n_initial = max(2, min(n_initial_points,
                                     (total_trials or 10) // 2 or 2))
        self._n_candidates = n_candidates
        self._xi = xi
        self._x: List[List[float]] = []
        self._y: List[float] = []
        self._pending: Dict[int, List[float]] = {}
        self._np_rng = np.random.default_rng(seed)

    # ---- BaseAdvisor hooks (called under the base lock) ----
    def _propose(self, trial_no: int) -> Proposal:
        if not self._dims or len(self._y) < self._n_initial:
            knobs = sample_knobs(self.knob_config, self._rng)
            vec = knobs_to_unit_vector(self.knob_config, knobs)
        else:
            vec = self._suggest()
            knobs = knobs_from_unit_vector(self.knob_config, vec, self._rng)
        self._pending[trial_no] = vec
        warm_start = ""
        # Warm-start from the incumbent only when the proposal's traced
        # shapes match it — otherwise loading its pytree would mis-shape.
        if (self.best is not None and self.best.trial_id
                and shape_signature(self.knob_config, knobs)
                == shape_signature(self.knob_config, self.best.knobs)):
            for n, k in self.knob_config.items():
                if isinstance(k, PolicyKnob) and k.policy == "SHARE_PARAMS":
                    knobs[n] = True
                    warm_start = self.best.trial_id
        return Proposal(trial_no=trial_no, knobs=knobs,
                        warm_start_trial_id=warm_start)

    def _feedback(self, result: TrialResult) -> None:
        vec = self._pending.pop(result.trial_no, None)
        if vec is None:
            vec = knobs_to_unit_vector(self.knob_config, result.knobs)
        self._x.append(vec)
        self._y.append(float(result.score))

    def _on_trial_errored(self, trial_no: int) -> None:
        self._pending.pop(trial_no, None)

    # ---- surrogate ----
    def _fit_gp(self, x: np.ndarray, y: np.ndarray):
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import ConstantKernel, Matern

        kernel = ConstantKernel(1.0) * Matern(
            length_scale=np.full(x.shape[1], 0.3), nu=2.5)
        gp = GaussianProcessRegressor(
            kernel=kernel, alpha=1e-6, normalize_y=True,
            n_restarts_optimizer=1,
            random_state=int(self._np_rng.integers(2 ** 31)))
        gp.fit(x, y)
        return gp

    def _suggest(self) -> List[float]:
        x = np.asarray(self._x, dtype=np.float64)
        y = np.asarray(self._y, dtype=np.float64)
        gp = self._fit_gp(x, y)
        # constant liar: impute pending points at posterior mean
        if self._pending:
            xp = np.asarray(list(self._pending.values()), dtype=np.float64)
            yp = gp.predict(xp)
            gp = self._fit_gp(np.vstack([x, xp]), np.concatenate([y, yp]))
            y_all = np.concatenate([y, yp])
        else:
            y_all = y
        best_y = float(np.max(y_all))
        cand = self._np_rng.random((self._n_candidates, len(self._dims)))
        # include jittered copies of the incumbent for local refinement
        inc = x[int(np.argmax(y))]
        local = np.clip(inc + self._np_rng.normal(
            0, 0.05, (self._n_candidates // 8, len(self._dims))), 0, 1)
        cand = np.vstack([cand, local])
        mu, sigma = gp.predict(cand, return_std=True)
        ei = _expected_improvement(mu, np.maximum(sigma, 1e-9),
                                   best_y, self._xi)
        return cand[int(np.argmax(ei))].tolist()


def _expected_improvement(mu: np.ndarray, sigma: np.ndarray, best_y: float,
                          xi: float) -> np.ndarray:
    from scipy.stats import norm

    imp = mu - best_y - xi
    z = imp / sigma
    return imp * norm.cdf(z) + sigma * norm.pdf(z)
