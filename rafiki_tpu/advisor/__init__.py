"""Hyperparameter-search advisors (random / Bayesian-GP / BOHB /
architecture evolution).

See SURVEY.md §2 "Advisor service" and §3.4 for the propose/feedback
protocol this package implements.
"""

from .base import (ADVISOR_REGISTRY, BaseAdvisor, Proposal, TrialResult,
                   make_advisor)
from .evolution import ArchEvolutionAdvisor
from .random_search import RandomAdvisor

ADVISOR_REGISTRY["random"] = RandomAdvisor
ADVISOR_REGISTRY["arch_evo"] = ArchEvolutionAdvisor

try:  # Bayesian-GP needs scikit-learn; register if available
    from .bayes_gp import BayesOptAdvisor

    ADVISOR_REGISTRY["bayes_gp"] = BayesOptAdvisor
except ImportError:  # pragma: no cover
    pass

try:
    from .hyperband import BOHBAdvisor

    ADVISOR_REGISTRY["bohb"] = BOHBAdvisor
except ImportError:  # pragma: no cover
    pass

__all__ = [
    "ADVISOR_REGISTRY", "BaseAdvisor", "Proposal", "TrialResult",
    "make_advisor", "RandomAdvisor", "ArchEvolutionAdvisor",
]
