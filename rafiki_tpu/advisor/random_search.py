"""Random search advisor (reference: the 'random' advisor algorithm,
SURVEY.md §2 "Advisor service")."""

from __future__ import annotations

from .base import BaseAdvisor, Proposal, TrialResult
from ..model.knob import PolicyKnob, sample_knobs


class RandomAdvisor(BaseAdvisor):
    name = "random"

    def _propose(self, trial_no: int) -> Proposal:
        knobs = sample_knobs(self.knob_config, self._rng)
        # enable param sharing when the model supports it and a best exists
        warm_start = ""
        if self.best is not None and self.best.trial_id:
            for n, k in self.knob_config.items():
                if isinstance(k, PolicyKnob) and k.policy == "SHARE_PARAMS":
                    knobs[n] = True
                    warm_start = self.best.trial_id
        return Proposal(trial_no=trial_no, knobs=knobs,
                        warm_start_trial_id=warm_start)

    def _feedback(self, result: TrialResult) -> None:
        pass
