"""Advisor core: the propose/feedback (ask/tell) loop.

Parity target: the reference's ``rafiki/advisor`` (SURVEY.md §2 "Advisor
service", §3.4): a train worker repeatedly asks for a :class:`Proposal`
(a knob assignment plus trial-control flags) and reports back a
(knobs, score) result; the advisor updates its posterior/bracket state.

The advisor is a plain in-process library here; ``advisor/service.py``
wraps it behind HTTP with the same two verbs (propose / feedback) for
cross-process workers.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..model.knob import (KnobConfig, Knobs, knob_config_from_json,
                          knob_config_to_json)


@dataclass
class Proposal:
    """One unit of work handed to a train worker."""

    trial_no: int
    knobs: Knobs
    #: fraction of full training budget to spend (BOHB rungs; 1.0 = full)
    budget_scale: float = 1.0
    #: param-sharing directive: trial id to warm-start from, or "" for none
    warm_start_trial_id: str = ""
    #: if False, the search is over and the worker should exit
    is_valid: bool = True
    #: free-form per-algorithm state echoed back in feedback (bracket ids…)
    meta: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "trial_no": self.trial_no,
            "knobs": self.knobs,
            "budget_scale": self.budget_scale,
            "warm_start_trial_id": self.warm_start_trial_id,
            "is_valid": self.is_valid,
            "meta": self.meta,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Proposal":
        return Proposal(
            trial_no=d["trial_no"],
            knobs=d["knobs"],
            budget_scale=d.get("budget_scale", 1.0),
            warm_start_trial_id=d.get("warm_start_trial_id", ""),
            is_valid=d.get("is_valid", True),
            meta=d.get("meta", {}),
        )

    @staticmethod
    def invalid() -> "Proposal":
        return Proposal(trial_no=-1, knobs={}, is_valid=False)


@dataclass
class TrialResult:
    """A completed trial as reported back to the advisor."""

    trial_no: int
    knobs: Knobs
    score: float
    trial_id: str = ""       # MetaStore/ParamStore id, for warm-start refs
    budget_scale: float = 1.0
    meta: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {"trial_no": self.trial_no, "knobs": self.knobs,
                "score": self.score, "trial_id": self.trial_id,
                "budget_scale": self.budget_scale, "meta": self.meta}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "TrialResult":
        return TrialResult(
            trial_no=d["trial_no"], knobs=d["knobs"], score=d["score"],
            trial_id=d.get("trial_id", ""),
            budget_scale=d.get("budget_scale", 1.0),
            meta=d.get("meta", {}))


class BaseAdvisor:
    """Thread-safe ask/tell hyperparameter search over a knob config.

    Subclasses implement ``_propose`` and ``_feedback``; the base class
    handles budget accounting (trial count / wall-clock), bookkeeping of
    results, best-trial tracking, and locking (multiple workers hit one
    advisor concurrently — SURVEY.md §3.4).
    """

    name = "base"

    def __init__(self, knob_config: KnobConfig,
                 total_trials: Optional[int] = None,
                 time_budget_s: Optional[float] = None,
                 seed: int = 0) -> None:
        self.knob_config = knob_config
        self.total_trials = total_trials
        self.time_budget_s = time_budget_s
        self._start_time = time.monotonic()
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._next_trial_no = 0
        self._outstanding: Dict[int, Proposal] = {}
        self.results: List[TrialResult] = []
        self.best: Optional[TrialResult] = None

    # ---- public API ----
    def propose(self) -> Proposal:
        with self._lock:
            if self._budget_exhausted():
                return Proposal.invalid()
            proposal = self._propose(self._next_trial_no)
            if not proposal.is_valid:
                return proposal
            proposal.trial_no = self._next_trial_no
            self._next_trial_no += 1
            self._outstanding[proposal.trial_no] = proposal
            return proposal

    def propose_batch(self, k: int) -> List[Proposal]:
        """Up to ``k`` proposals under ONE lock acquisition — the gang
        engine's lane-fill primitive.

        Atomicity is the determinism guarantee: no concurrent worker can
        interleave a propose/feedback between batch members, so for a
        given advisor seed and feedback history the batch equals ``k``
        sequential :meth:`propose` calls exactly — same knob sets
        regardless of lane count (tier-1 asserts this for the random and
        BOHB advisors). Returns fewer than ``k`` (possibly zero)
        proposals when the budget runs out mid-batch."""
        out: List[Proposal] = []
        with self._lock:
            for _ in range(max(0, k)):
                if self._budget_exhausted():
                    break
                proposal = self._propose(self._next_trial_no)
                if not proposal.is_valid:
                    break
                proposal.trial_no = self._next_trial_no
                self._next_trial_no += 1
                self._outstanding[proposal.trial_no] = proposal
                out.append(proposal)
        return out

    def feedback(self, result: TrialResult) -> None:
        with self._lock:
            self._feedback_locked(result)

    def feedback_batch(self, results: Sequence[TrialResult]) -> None:
        """Report a batch of completed lanes atomically (order preserved:
        rung/posterior state sees them in the given sequence, same as
        sequential feedback calls)."""
        with self._lock:
            for result in results:
                self._feedback_locked(result)

    def _feedback_locked(self, result: TrialResult) -> None:
        self._outstanding.pop(result.trial_no, None)
        self.results.append(result)
        # Only full-budget trials compete for "best" (a BOHB low-rung
        # score is not comparable to a full train).
        if result.budget_scale >= 1.0 and (
                self.best is None or result.score > self.best.score):
            self.best = result
        self._feedback(result)

    def trial_errored(self, trial_no: int) -> None:
        """Reference semantics: an errored trial is dropped and the budget
        moves on (SURVEY.md §5.3)."""
        with self._lock:
            self._outstanding.pop(trial_no, None)
            self._on_trial_errored(trial_no)

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._budget_exhausted() and not self._outstanding

    @property
    def best_effort(self) -> Optional[TrialResult]:
        """``best`` when a full-budget trial exists, else the top scorer
        among the highest-budget completed trials (scores are only
        comparable within one budget level)."""
        with self._lock:
            if self.best is not None:
                return self.best
            if not self.results:
                return None
            max_budget = max(r.budget_scale for r in self.results)
            candidates = [r for r in self.results
                          if r.budget_scale >= max_budget - 1e-9]
            return max(candidates, key=lambda r: r.score)

    # ---- subclass interface ----
    def _propose(self, trial_no: int) -> Proposal:
        raise NotImplementedError

    def _feedback(self, result: TrialResult) -> None:
        raise NotImplementedError

    def _on_trial_errored(self, trial_no: int) -> None:
        pass

    # ---- internals ----
    def _budget_exhausted(self) -> bool:
        if self.total_trials is not None and \
                self._next_trial_no >= self.total_trials:
            return True
        if self.time_budget_s is not None and \
                time.monotonic() - self._start_time > self.time_budget_s:
            return True
        return False


# populated by rafiki_tpu.advisor.__init__ to avoid import cycles
ADVISOR_REGISTRY: Dict[str, Any] = {}


def make_advisor(knob_config: KnobConfig, advisor_type: str = "auto",
                 **kwargs: Any) -> BaseAdvisor:
    """Factory mirroring the reference's ``make_advisor``.

    ``advisor_type='auto'`` picks Bayesian-GP for small continuous spaces,
    BOHB when the model declares budget policies, random otherwise.
    """
    from ..model.knob import PolicyKnob, tunable_knobs

    if advisor_type == "auto":
        has_budget_policy = any(
            isinstance(k, PolicyKnob) and
            k.policy in ("QUICK_TRAIN", "EARLY_STOP")
            for k in knob_config.values())
        if has_budget_policy:
            advisor_type = "bohb"
        elif tunable_knobs(knob_config):
            advisor_type = "bayes_gp"
        else:
            advisor_type = "random"
        # degrade along the preference chain if a dependency is missing
        for fallback in (advisor_type, "bayes_gp", "random"):
            if fallback in ADVISOR_REGISTRY:
                advisor_type = fallback
                break
    cls = ADVISOR_REGISTRY.get(advisor_type)
    if cls is None:
        raise ValueError(
            f"unknown advisor type {advisor_type!r}; "
            f"available: {sorted(ADVISOR_REGISTRY)}")
    return cls(knob_config, **kwargs)
