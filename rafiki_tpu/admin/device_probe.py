"""Device inventory probe, run as a throwaway subprocess.

The ServicesManager must learn the slice topology without initializing the
accelerator runtime in its own process — on a TPU-VM, whichever process
first opens the chips owns them, and the manager's job is to hand them to
trial workers, not hold them (SURVEY.md §7 "Device multi-tenancy"). So it
execs this module, which imports jax, dumps the inventory as one JSON line,
and exits, releasing the chips.
"""

from __future__ import annotations

import json
import sys


def probe() -> dict:
    from ..utils.platform import apply_platform_env

    apply_platform_env()
    import jax

    devices = []
    for d in jax.devices():
        devices.append({
            "id": d.id,
            "platform": d.platform,
            "coords": list(getattr(d, "coords", None) or []) or None,
            "core_on_chip": getattr(d, "core_on_chip", 0),
        })
    return {"platform": jax.default_backend(), "devices": devices}


if __name__ == "__main__":
    json.dump(probe(), sys.stdout)
    sys.stdout.write("\n")
    sys.exit(0)
