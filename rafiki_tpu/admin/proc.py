"""Process-identity helpers shared by the control plane's kill/adopt paths.

Every place the control plane acts on a *recorded* pid (killing
orphans in ``stack.py``, adopting survivors in the ServicesManager's
boot reconciler) faces the same hazard: between the row being written
and the action, the process may have exited and the kernel may have
handed the pid to an unrelated program. Matching on cmdline text alone
(the original guard) still mistakes a *new* rafiki process for the
recorded one. The hardened identity is ``(pid, start_time)`` where
``start_time`` is field 22 of ``/proc/<pid>/stat`` — the kernel's
jiffies-since-boot stamp of process creation, immutable for the life
of the pid and never equal across a recycle. The MetaStore records it
at spawn; any later kill or adoption requires it to match.
"""

from __future__ import annotations

import os
from typing import Optional


def pid_alive(pid: int) -> bool:
    """Signal-0 liveness probe (EPERM counts as alive: it exists)."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def proc_start_time(pid: int) -> float:
    """Kernel start time of ``pid`` (field 22 of ``/proc/<pid>/stat``,
    jiffies since boot), or 0.0 when the process is gone / unreadable.
    The comm field (2) may contain spaces and parentheses, so parse
    from AFTER the last ``)``."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode(errors="replace")
    except OSError:
        return 0.0
    _, _, rest = stat.rpartition(")")
    fields = rest.split()
    # rest starts at field 3 ("state"); start_time is field 22
    if len(fields) < 20:
        return 0.0
    return float(fields[19])


def proc_state(pid: int) -> str:
    """Single-char process state (``R``/``S``/``Z``/...), or ``""``
    when gone. A zombie still has a /proc entry but is dead for every
    purpose the control plane cares about."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode(errors="replace")
    except OSError:
        return ""
    _, _, rest = stat.rpartition(")")
    fields = rest.split()
    return fields[0] if fields else ""


def cmdline_is_ours(pid: int) -> bool:
    """Weak identity: the process cmdline looks like a rafiki service
    (module path or the kv daemon). Necessary but NOT sufficient — pair
    with :func:`identity_matches` wherever a recorded ``start_time``
    exists."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return False
    return "rafiki" in cmd


def identity_matches(pid: int, start_time: float) -> bool:
    """Hardened pid identity: alive (not a zombie), cmdline ours, and —
    when a start time was recorded at spawn — the kernel start time
    matches exactly. A recycled pid can never pass: even another rafiki
    process on the same pid has a different ``start_time``."""
    if not pid_alive(pid) or proc_state(pid) == "Z":
        return False
    if not cmdline_is_ours(pid):
        return False
    if start_time and proc_start_time(pid) != start_time:
        return False
    return True


def terminate_pid(pid: int, start_time: float = 0.0,
                  grace_s: float = 5.0) -> bool:
    """SIGTERM→wait→SIGKILL a recorded pid, re-checking identity before
    EACH signal (the guard must hold at kill time, not just at scan
    time). Returns True when the process is gone afterwards."""
    import signal
    import time

    if not identity_matches(pid, start_time):
        return not pid_alive(pid) or proc_state(pid) == "Z"
    try:
        os.kill(pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return not pid_alive(pid)
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if not pid_alive(pid) or proc_state(pid) == "Z":
            return True
        time.sleep(0.05)
    if identity_matches(pid, start_time):
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if not pid_alive(pid) or proc_state(pid) == "Z":
            return True
        time.sleep(0.05)
    return False


class AdoptedProcess:
    """Popen-shaped handle over a process this manager did NOT spawn.

    A restarted admin re-adopts the previous admin's surviving children
    by pid; they are not our children, so there is no ``Popen`` and no
    wait status. This mimic covers exactly the surface
    ``ManagedService``/``ServicesManager`` use: ``pid``, ``poll()``,
    ``returncode``, ``terminate()``, ``kill()``, ``wait(timeout)``.
    Liveness is judged through :func:`identity_matches` with the
    recorded start time, so a recycled pid reads as dead rather than as
    somebody else's process. Exit codes of non-children are unknowable;
    an adopted process that vanishes reports :data:`ADOPTED_EXIT`
    (non-zero → the crash/respawn path, the safe default: a clean
    drain is re-spawnable, a missed crash is not healable).
    """

    #: stand-in returncode for adopted processes (never 0: unknown
    #: death must flow into the respawn path, not be read as a drain)
    ADOPTED_EXIT = 97

    def __init__(self, pid: int, start_time: float = 0.0) -> None:
        self.pid = pid
        self.start_time = start_time or proc_start_time(pid)
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if identity_matches(self.pid, self.start_time):
            return None
        self.returncode = self.ADOPTED_EXIT
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        import subprocess
        import time

        deadline = None if timeout is None else \
            time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(
                    f"adopted:{self.pid}", timeout)
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]

    def _signal(self, sig: int) -> None:
        if not identity_matches(self.pid, self.start_time):
            self.poll()
            return
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def terminate(self) -> None:
        import signal

        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        import signal

        self._signal(signal.SIGKILL)
