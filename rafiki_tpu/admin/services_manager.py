"""ServicesManager: spawn/track service processes on TPU sub-meshes.

Parity target: the reference's ``ServicesManager`` + ``ContainerManager``
pair (SURVEY.md §2 "Admin"/"Container manager", §3.1/§3.2): the control
plane spawns an advisor plus N train workers per train job, and a predictor
plus N inference workers per inference job. The rebuild replaces "Docker
service with one GPU" by "host process pinned to an ICI-contiguous TPU
sub-mesh" via env vars (``TPU_VISIBLE_CHIPS`` et al., SURVEY.md §7):

- Topology discovery runs in a throwaway probe subprocess so the manager
  never holds the chips itself (``device_probe.py``).
- A :class:`SubMeshAllocator` hands each worker a slot; the slot's env
  vars confine the child's JAX runtime to those chips.
- Service rows land in the MetaStore exactly as the reference records its
  Docker services; ``poll()`` is the failure detector (SURVEY.md §5.3).
- The data plane (param blobs + query queues) is one ``rafiki-kvd``
  process per stack (the Redis container equivalent, SURVEY.md §5.8(b)).

Crash-only control plane (the orchestrator-recovery duty of
arXiv:1804.06087, which Docker Swarm carried for the reference): every
spawn persists its FULL recipe (``spawn_spec``) and the child's kernel
start time into the service row, so the row — not this object's dicts —
is the source of truth. A restarted admin calls :meth:`reconcile` to
re-ADOPT surviving children (identity-checked pid + health probe, slots
re-reserved), crash-and-respawn the dead ones under the durable respawn
budget, and reap orphans whose job was stopped meanwhile. A
single-writer lease row (generation-fenced) keeps a stale or duplicate
admin from spawning a second stack on chips the first still holds.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..constants import (ServiceStatus, ServiceType, SubTrainJobStatus,
                         TaskType, TrainJobStatus)
from ..parallel.mesh import DeviceSpec, SubMesh, SubMeshAllocator, \
    submesh_env_vars
from ..store.meta_store import MetaStore
from .autoscaler import AutoscaleConfig, AutoscalePolicy
from .proc import (AdoptedProcess, identity_matches, proc_start_time,
                   terminate_pid)

#: service rows in these states are settled history — never adopted,
#: respawned, or reaped again
_TERMINAL = (ServiceStatus.STOPPED, ServiceStatus.ERRORED,
             ServiceStatus.CRASHED)

#: worker service types eligible for self-healing respawn
_WORKER_TYPES = (ServiceType.TRAIN_WORKER, ServiceType.INFERENCE_WORKER)


class LeaseHeldError(RuntimeError):
    """Another live admin holds the single-writer lease for this
    MetaStore — booting a second control plane would double-spawn the
    stack. Carries the holder/generation for a structured error."""

    def __init__(self, lease: Dict[str, Any]) -> None:
        self.lease = dict(lease)
        age = time.time() - float(lease.get("heartbeat_at") or 0)
        super().__init__(
            f"admin lease held by {lease.get('holder', '?')[:12]} "
            f"(generation {lease.get('generation')}, heartbeat "
            f"{age:.1f}s ago) — a live admin owns this MetaStore; "
            "stop it first or wait for its lease to expire")


class AdminFencedError(RuntimeError):
    """This manager LOST the lease (a newer admin took over): every
    mutating operation is refused so the two control planes cannot
    fight over the same processes and chips."""


class ManagedService:
    """One spawned child process + its MetaStore row + its device slot."""

    def __init__(self, service_id: str, service_type: str,
                 proc: subprocess.Popen, slot: Optional[SubMesh] = None,
                 host: str = "", port: int = 0,
                 adopted: bool = False) -> None:
        self.service_id = service_id
        self.service_type = service_type
        self.proc = proc
        self.slot = slot
        self.host = host
        self.port = port
        #: True when this handle was rebuilt around a surviving pid by
        #: the boot reconciler rather than spawned by this manager
        self.adopted = adopted

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def alive(self) -> bool:
        return self.proc.poll() is None




def probe_devices(timeout: float = 120.0) -> Dict[str, Any]:
    """Run the device probe subprocess; returns {platform, devices}."""
    out = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.admin.device_probe"],
        capture_output=True, text=True, timeout=timeout, check=True,
        env=os.environ.copy())
    return json.loads(out.stdout.strip().splitlines()[-1])


class ServicesManager:
    def __init__(self, meta_store: MetaStore, workdir: str,
                 slot_size: int = 1, platform: Optional[str] = None,
                 devices: Optional[List[DeviceSpec]] = None,
                 slot_timeout: float = 30.0,
                 default_workers: int = 1) -> None:
        self.meta = meta_store
        self.slot_timeout = slot_timeout
        #: train workers per job when the budget names no WORKER_COUNT /
        #: GPU_COUNT (the CLI's --workers)
        self.default_workers = max(1, int(default_workers))
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        if devices is None:
            inv = probe_devices()
            platform = platform or inv["platform"]
            devices = [DeviceSpec.from_probe(d) for d in inv["devices"]]
        self.platform = platform or "cpu"
        self.devices = devices
        self.allocator = SubMeshAllocator(devices, slot_size)
        #: serializes spawn/stop/poll across the admin + monitor threads
        #: (e.g. the monitor must not reap an advisor between its spawn and
        #: its workers' spawn)
        self.op_lock = threading.RLock()
        self.services: Dict[str, ManagedService] = {}
        self.kv_host: str = ""
        self.kv_port: int = 0
        self._kv_proc: Optional[subprocess.Popen] = None
        self._kv_server: Any = None
        #: self-healing: spawn spec per live service so a CRASHED worker
        #: (train or inference) can be respawned while its parent job is
        #: still RUNNING. Lineage = (type, job id): the restart budget is
        #: shared by a job's workers so a crash-looping config converges.
        self._respawn_specs: Dict[str, Dict[str, Any]] = {}
        #: in-memory mirror of the DURABLE respawn_budgets table — the
        #: store is authoritative (increments write through), so the
        #: budget survives an admin crash/restart
        self._respawn_counts: Dict[Any, int] = \
            self._load_respawn_counts()
        #: max replacement spawns per (service type, job) lineage
        self.max_respawns = 3
        #: respawns that found no free slot, retried on every poll —
        #: without this, a single-worker job whose only slot got snatched
        #: between release and re-acquire would lose healing forever
        self._pending_respawns: List[Dict[str, Any]] = []
        #: jobs whose self-healing is exhausted or lost (respawn budget
        #: spent, queued respawn dropped): job id → reason. Surfaced on
        #: the admin /health so a job quietly running under-replicated
        #: (or not at all) is visible, not just a log line.
        self._degraded: Dict[str, str] = {}
        #: completed drain→stop→respawn cycles (rolling_restart)
        self._rolling_restarts = 0
        #: one rolling restart at a time: a concurrent second call (an
        #: operator retrying a timed-out request) would drain the fresh
        #: replacements and spawn duplicates sharing one worker id
        self._rolling_lock = threading.Lock()
        #: single-writer admin lease (generation-fenced). Opt-in:
        #: acquire_lease() arms it; a manager that never acquires (unit
        #: tests, embedded use) is never fenced.
        self.lease_holder = uuid.uuid4().hex
        self.lease_generation = 0
        self.lease_ttl_s = 15.0
        self._lease_held = False
        self.fenced = False
        #: boot-reconciler outcome counters, surfaced on the admin
        #: /metrics (services_adopted / orphans_reaped / ...) and in
        #: the /health recovery block + dashboard banner
        from ..obs.metrics import StatsMap

        self.recovery = StatsMap({
            "services_adopted": 0, "services_crashed": 0,
            "orphans_reaped": 0, "respawns_queued": 0,
            "kv_adopted": 0, "kvd_respawns": 0,
            "kvd_replay_seconds": 0.0, "lease_takeovers": 0,
            "last_recovery_at": 0.0})
        #: kvd persistence: where the WAL + snapshot live (recorded in
        #: the spawn spec so a restarted admin respawns WITH replay)
        self._kv_data_dir: str = ""
        #: cached kvd STATS (scrapes must not open a socket per hit);
        #: guarded by its own lock — never op_lock, a scrape must not
        #: contend with a slow spawn
        self._kvd_stats_cache: Dict[str, Any] = {}
        self._kvd_stats_at = 0.0
        self._kvd_stats_lock = threading.Lock()
        #: consecutive failed kvd boot attempts (one per monitor tick)
        self._kv_boot_attempts = 0
        #: horizontal scale-out state per inference job: routing pool,
        #: spawn template for extra replicas, autoscale policy (when
        #: the budget armed one), warming/draining workers in flight.
        #: Rebuilt lazily from live services + the job budget after an
        #: admin restart (_ensure_scaleout), so adoption keeps scaling.
        self._scaleout: Dict[str, Dict[str, Any]] = {}
        self._last_autoscale_tick = 0.0
        self._pool_hub_cache: Any = None
        self._pool_hub_key: Any = None
        #: autoscaler action counters, surfaced on admin /metrics
        self.scaling = StatsMap({
            "autoscale_ups": 0, "autoscale_downs": 0,
            "autoscale_blocked": 0, "pool_publishes": 0})

    def _load_respawn_counts(self) -> Dict[Any, int]:
        """Durable lineage budgets → the (type, job_id)-keyed mirror."""
        out: Dict[Any, int] = {}
        try:
            for lineage, count in self.meta.get_respawn_counts().items():
                stype, _, job_id = lineage.partition(":")
                out[(stype, job_id)] = int(count)
        except Exception:  # noqa: BLE001 — a pre-migration store must
            # not break boot; budgets then start fresh (old behavior)
            import logging

            logging.getLogger(__name__).warning(
                "could not load durable respawn budgets", exc_info=True)
        return out

    # ---- admin lease (single-writer fencing) ----
    def acquire_lease(self, ttl_s: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Claim the MetaStore's single-writer admin lease, or raise
        :class:`LeaseHeldError` when a live admin already owns it. A
        takeover of an EXPIRED lease bumps the generation (counted as
        ``lease_takeovers``) — the old holder's next renew fails and
        fences it out."""
        if ttl_s is not None:
            self.lease_ttl_s = float(ttl_s)
        got = self.meta.acquire_admin_lease(self.lease_holder,
                                            ttl_s=self.lease_ttl_s)
        if got is None:
            raise LeaseHeldError(self.meta.get_admin_lease() or {})
        self._lease_held = True
        self.fenced = False
        self.lease_generation = int(got["generation"])
        if got.get("took_over"):
            self.recovery.inc("lease_takeovers")
        return got

    def start_lease_heartbeat(self,
                              interval_s: Optional[float] = None) -> None:
        """Start the background lease-renewal thread (idempotent).

        Call IMMEDIATELY after :meth:`acquire_lease` — before
        :meth:`reconcile`: reconciling can legitimately exceed the TTL
        (per-orphan SIGTERM/SIGKILL grace, health probes), and with no
        heartbeat a concurrent boot would "take over" from a live admin
        mid-reconcile. The thread is deliberately independent of the
        admin's monitor loop: it never touches op_lock, so a blocking
        spawn cannot starve it. It exits on release/fence."""
        if getattr(self, "_hb_thread", None) is not None and \
                self._hb_thread.is_alive():
            return
        if not self._lease_held:
            return
        tick = interval_s if interval_s is not None else \
            max(0.2, min(self.lease_ttl_s / 3.0, 5.0))
        self._hb_stop = threading.Event()

        def loop() -> None:
            while not self._hb_stop.wait(tick):
                try:
                    if not self.renew_lease():
                        return  # fenced: nothing left to renew
                except Exception:  # a store hiccup must not kill the
                    # heartbeat — the next tick retries
                    import logging

                    logging.getLogger(__name__).warning(
                        "lease heartbeat failed", exc_info=True)

        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def _stop_lease_heartbeat(self) -> None:
        stop = getattr(self, "_hb_stop", None)
        if stop is not None:
            stop.set()
        th = getattr(self, "_hb_thread", None)
        if th is not None and th.is_alive():
            th.join(timeout=5)
        self._hb_thread = None

    def renew_lease(self) -> bool:
        """Heartbeat the held lease. False (and ``self.fenced``) when a
        newer admin took over — from then on every spawn/stop raises
        and stop_all releases handles WITHOUT killing, because the
        children now belong to the new admin."""
        if not self._lease_held or self.fenced:
            return not self.fenced
        if self.meta.renew_admin_lease(self.lease_holder):
            return True
        import logging

        logging.getLogger(__name__).error(
            "admin lease lost (a newer admin took over) — fencing this "
            "manager: no further spawns/stops")
        self.fenced = True
        return False

    def release_lease(self) -> None:
        """Clean shutdown: expire the lease instantly so the next admin
        boots without waiting out the TTL. Stops the heartbeat FIRST so
        a late renew cannot resurrect the released lease."""
        self._stop_lease_heartbeat()
        if self._lease_held and not self.fenced:
            try:
                self.meta.release_admin_lease(self.lease_holder)
            except Exception:  # noqa: BLE001 — shutdown must not die
                # on a store hiccup; the TTL covers the release anyway
                import logging

                logging.getLogger(__name__).warning(
                    "admin lease release failed (the TTL will expire "
                    "it)", exc_info=True)
        self._lease_held = False

    def _check_fence(self) -> None:
        if self.fenced:
            raise AdminFencedError(
                "admin lease lost — this manager is fenced; a newer "
                "admin owns the stack now")

    def reap_stale_services(self) -> int:
        """Scorched-earth restart cleanup: kill every process a
        previous admin's non-terminal rows still point at and mark the
        rows STOPPED. :meth:`reconcile` (which ADOPTS survivors instead
        of killing them) is the normal boot path; this remains for
        operators who explicitly want a cold start. Kills are gated on
        the hardened pid identity — recorded start time included — so a
        recycled pid is never killed."""
        reaped = 0
        for row in self.meta.get_services():
            if row["status"] in _TERMINAL:
                continue
            if row["id"] in self.services:  # owned by THIS manager
                continue
            pid = int(row.get("pid") or 0)
            if pid > 0:
                terminate_pid(pid, float(row.get("start_time") or 0))
            self.meta.update_service(row["id"],
                                     status=ServiceStatus.STOPPED)
            reaped += 1
        return reaped

    # ---- boot reconciler (crash-only control plane) ----
    def reconcile(self) -> Dict[str, Any]:
        """Rebuild the process table from the MetaStore after an admin
        death. For every non-terminal service row left by the previous
        admin:

        - **adopt** survivors: pid alive + hardened identity (cmdline
          AND recorded kernel start time) + health probe on the
          recorded HTTP/obs port → a :class:`ManagedService` handle is
          rebuilt around the pid, its sub-mesh slot re-reserved, and
          its respawn spec re-registered — streams and trials keep
          running, nothing is restarted;
        - **crash** the dead: rows whose process is gone (or failed the
          identity/probe check) go CRASHED; crashed WORKERS of a
          still-RUNNING job flow into the existing respawn path under
          the durable respawn budget;
        - **reap** orphans: survivors whose job was stopped while the
          admin was down are killed (identity-gated) and marked
          STOPPED.

        The kvd data plane is adopted the same way (PING on the
        recorded port), so param blobs and in-flight queues survive the
        admin dying. Returns the recovery counter snapshot.
        """
        with self.op_lock:
            # op_lock intentionally serializes whole admin operations,
            # terminate/spawn waits included — overlapping reconciles
            # would double-spawn; see "Admin op serialization" in
            # docs/linting.md
            return self._reconcile()  # rafiki: noqa[lock-order-cycle]

    def _reconcile(self) -> Dict[str, Any]:
        import logging

        log = logging.getLogger(__name__)
        self._respawn_counts = self._load_respawn_counts()
        crashed_workers: List[Dict[str, Any]] = []
        for row in self.meta.get_services():
            if row["status"] in _TERMINAL or row["id"] in self.services:
                continue
            stype = row["service_type"]
            if stype == ServiceType.DATA_PLANE:
                self._reconcile_data_plane(row)
                continue
            pid = int(row.get("pid") or 0)
            start_time = float(row.get("start_time") or 0)
            spec = row.get("spawn_spec") or None
            job_id = row.get("train_job_id") or \
                row.get("inference_job_id")
            job = None
            if job_id:
                job = self.meta.get_train_job(job_id) or \
                    self.meta.get_inference_job(job_id)
            job_running = bool(job and job.get("status") == "RUNNING")
            alive = identity_matches(pid, start_time)

            if alive and job_id and not job_running:
                # orphan: its job was stopped/finished while no admin
                # was alive to stop the process
                log.info("reaping orphan %s %s (job %s is %s)",
                         stype, row["id"], job_id,
                         job.get("status") if job else "gone")
                terminate_pid(pid, start_time)
                self.meta.update_service(row["id"],
                                         status=ServiceStatus.STOPPED)
                self.recovery.inc("orphans_reaped")
                continue

            probe = self._probe_service(row, spec) if alive else False
            if alive and probe is not False:
                if self._adopt_service(row, spec, pid, start_time):
                    continue
                # un-adoptable (slot conflict): fall through to crash
                alive = False

            # dead / identity mismatch / failed probe → CRASHED
            if alive or identity_matches(pid, start_time):
                # process exists but is not serving: kill it before
                # respawning a replacement or two claim one slot
                terminate_pid(pid, start_time)
            self.meta.update_service(row["id"],
                                     status=ServiceStatus.CRASHED)
            self.recovery.inc("services_crashed")
            if job_running and spec and stype in _WORKER_TYPES:
                crashed_workers.append({"dead_id": row["id"],
                                        "spec": spec})

        # crashed workers flow into the EXISTING respawn path, under
        # the budget that survived the restart
        for item in crashed_workers:
            try:
                if not self._respawn(item["dead_id"], item["spec"]):
                    self._pending_respawns.append(item)
                    self.recovery.inc("respawns_queued")
            except Exception as e:  # noqa: BLE001 — reconcile must
                # finish; a failed respawn is a degraded job, not a
                # dead control plane
                log.warning("boot respawn of %s failed: %s",
                            item["dead_id"], e)
                mk = item["spec"].get("meta_kwargs") or {}
                self._mark_degraded(
                    item["spec"]["service_type"],
                    mk.get("train_job_id") or mk.get("inference_job_id"),
                    f"boot respawn failed: {e}")
        self.recovery.set("last_recovery_at", time.time())
        return self.recovery_stats()

    def _adopt_service(self, row: Dict[str, Any],
                       spec: Optional[Dict[str, Any]], pid: int,
                       start_time: float) -> bool:
        """Rebuild a ManagedService handle around a surviving pid.
        False when its recorded sub-mesh cannot be re-reserved (the
        caller then treats it as crashed)."""
        import logging

        stype = row["service_type"]
        slot = None
        if spec and spec.get("needs_slot"):
            try:
                devices = json.loads(row.get("devices") or "[]")
            except ValueError:
                devices = []
            slot = self.allocator.reserve(devices)
            if slot is None:
                logging.getLogger(__name__).warning(
                    "cannot adopt %s %s: its recorded sub-mesh %r is "
                    "no longer free", stype, row["id"], devices)
                return False
        svc = ManagedService(
            row["id"], stype, AdoptedProcess(pid, start_time), slot,
            host=row.get("host") or "127.0.0.1",
            port=int(row.get("port") or 0), adopted=True)
        self.services[row["id"]] = svc
        if spec and stype in _WORKER_TYPES:
            self._respawn_specs[row["id"]] = {
                "module": spec["module"], "config": spec["config"],
                "service_type": stype,
                "needs_slot": bool(spec.get("needs_slot")),
                "meta_kwargs": dict(spec.get("meta_kwargs") or {})}
        self.meta.update_service(row["id"],
                                 status=ServiceStatus.RUNNING)
        self.recovery.inc("services_adopted")
        return True

    def _probe_service(self, row: Dict[str, Any],
                       spec: Optional[Dict[str, Any]]
                       ) -> Optional[bool]:
        """Health-probe a candidate's recorded HTTP surface: the row's
        own port (advisor/predictor) or the worker's obs sidecar (port
        discovered from its ``obs_port_file``). ANY HTTP answer —
        including an error status — counts as alive (the process is
        serving; not every service has /health). None = no probe
        channel recorded: identity alone must decide."""
        import urllib.error

        from ..utils.http import json_request

        host = row.get("host") or "127.0.0.1"
        port = int(row.get("port") or 0)
        if port <= 0:
            cfg = (spec or {}).get("config") or {}
            port_file = cfg.get("obs_port_file")
            if port_file and Path(port_file).exists():
                try:
                    port = int(Path(port_file).read_text().strip())
                except (OSError, ValueError):
                    port = 0
        if port <= 0:
            return None
        try:
            json_request("GET", f"http://{host}:{port}/health",
                         timeout=3.0)
            return True
        except urllib.error.HTTPError:
            return True  # it answered — alive, just no /health route
        except (OSError, ValueError):
            return False  # refused/timeout/garbage: not serving

    def _reconcile_data_plane(self, row: Dict[str, Any]) -> None:
        """Adopt a surviving rafiki-kvd (param blobs + queues live in
        its memory — killing it would drop every in-flight stream and
        deployed trial's params). A DEAD kvd whose row records a data
        dir is respawned on the SAME port with WAL replay — "row
        present, process dead" is a recovery case, never a cold
        start."""
        import logging

        from .proc import pid_alive

        pid = int(row.get("pid") or 0)
        start_time = float(row.get("start_time") or 0)
        host, port = row.get("host") or "127.0.0.1", \
            int(row.get("port") or 0)
        spec_cfg = (row.get("spawn_spec") or {}).get("config") or {}
        ok = False
        # identity first (recycled pid must not be PINGed as ours);
        # kvd's cmdline is "rafiki-kvd ..." so cmdline_is_ours holds
        if port > 0 and pid_alive(pid) and identity_matches(
                pid, start_time):
            try:
                from ..native.client import KVClient

                c = KVClient(host, port, connect_timeout=3.0)
                ok = c.ping()
                c.close()
            except (OSError, RuntimeError):
                ok = False  # refused / protocol error: not a live kvd
        if ok:
            self.kv_host, self.kv_port = host, port
            self._kv_data_dir = str(spec_cfg.get("data_dir") or "")
            server = _AdoptedKVServer(host, port,
                                      AdoptedProcess(pid, start_time))
            self._kv_server = server
            self._kv_proc = server._proc
            self._kv_service_id = row["id"]
            self.recovery.inc("kv_adopted")
            logging.getLogger(__name__).info(
                "adopted data plane kvd pid %d on %s:%d", pid, host,
                port)
            return
        if identity_matches(pid, start_time):
            terminate_pid(pid, start_time)
        self.meta.update_service(row["id"],
                                 status=ServiceStatus.CRASHED)
        self.recovery.inc("services_crashed")
        if port > 0 and spec_cfg.get("data_dir"):
            # respawn-with-replay on the recorded address: surviving
            # workers/predictors reconnect to the same host:port and
            # the WAL restores blobs, membership, queued messages
            self.kv_host, self.kv_port = host, port
            self._kv_data_dir = str(spec_cfg["data_dir"])
            self._kv_service_id = row["id"]
            self._kv_proc = _DeadProc()  # respawn path's "died" handle
            self._respawn_data_plane("dead at admin reconcile")

    def recovery_stats(self) -> Dict[str, Any]:
        """Reconciler + lease counters for /metrics, /health, and the
        dashboard recovery banner."""
        out = self.recovery.snapshot()
        out["lease_generation"] = self.lease_generation
        out["fenced"] = bool(self.fenced)
        return out

    # ---- data plane ----
    #: kvd WAL fsync policy (overridable via RAFIKI_KVD_FSYNC):
    #: `everysec` matches the Redis default — at most ~1s of
    #: acknowledged writes lost to a HOST crash; a process crash
    #: (kill -9, OOM) loses nothing under any policy because the
    #: records are already written to the fd
    KVD_FSYNC_DEFAULT = "everysec"

    def start_data_plane(self) -> None:
        """Boot the kvd data plane with WAL + snapshot persistence
        under ``workdir/kvd-data`` (no-op when already running or
        adopted by :meth:`reconcile`). The full boot recipe — data dir,
        fsync policy, host/port — persists in the service row's spawn
        spec, so both this admin's monitor and a RESTARTED admin can
        respawn a dead kvd with replay instead of cold-starting an
        empty one."""
        if self.kv_port:
            return  # already running or adopted by reconcile()
        self._check_fence()
        data_dir = str(self.workdir / "kvd-data")
        fsync = os.environ.get("RAFIKI_KVD_FSYNC",
                               self.KVD_FSYNC_DEFAULT)
        self._boot_data_plane("127.0.0.1", 0, data_dir, fsync)

    def _boot_data_plane(self, host: str, port: int, data_dir: str,
                         fsync: str) -> None:
        """Spawn a kvd (fresh or respawn-with-replay when ``port`` is
        pinned and the data dir already holds a WAL) and record its
        row + spawn spec."""
        from ..native.client import KVServer

        server = KVServer(host=host, port=port, data_dir=data_dir,
                          fsync=fsync)
        self._kv_server = server
        self._kv_proc = server._proc
        self.kv_host, self.kv_port = server.host, server.port
        self._kv_data_dir = data_dir
        row = self.meta.create_service(
            ServiceType.DATA_PLANE, host=server.host, port=server.port,
            pid=server._proc.pid,
            spawn_spec={"module": "rafiki-kvd",
                        "config": {"data_dir": data_dir,
                                   "fsync": fsync,
                                   "host": server.host,
                                   "port": server.port},
                        "service_type": ServiceType.DATA_PLANE,
                        "needs_slot": False, "meta_kwargs": {}},
            start_time=proc_start_time(server._proc.pid))
        self._kv_service_id = row["id"]
        self.meta.update_service(row["id"],
                                 status=ServiceStatus.RUNNING)
        # replay time is the recovery-latency half the bench measures;
        # stats() may briefly race the listener coming up — best-effort
        try:
            st = self._fresh_kvd_stats()
            self.recovery.set("kvd_replay_seconds",
                              float(st.get("replay_seconds") or 0.0))
        except (OSError, RuntimeError) as e:
            import logging

            logging.getLogger(__name__).warning(
                "could not read kvd replay stats: %s", e)

    def _respawn_data_plane(self, reason: str) -> bool:
        """Respawn a dead kvd on its RECORDED host:port + data dir —
        clients reconnect to the same address and the WAL replay
        restores blobs, pool membership, and queued messages. Budgeted
        like worker respawns (persisted lineage ``(DATA_PLANE, kvd)``)
        so a crash-looping data dir converges to a loud degraded state
        instead of a respawn storm. Returns True when a kvd is
        serving again."""
        import logging

        log = logging.getLogger(__name__)
        host, port = self.kv_host, self.kv_port
        data_dir = self._kv_data_dir or str(self.workdir / "kvd-data")
        lineage = (ServiceType.DATA_PLANE, "kvd")
        if self._respawn_counts.get(lineage, 0) >= self.max_respawns:
            log.error(
                "kvd respawn budget exhausted (%s) — the data plane "
                "appears to crash deterministically; stack is degraded "
                "until an operator intervenes", reason)
            self._degraded["data-plane"] = \
                "kvd respawn budget exhausted"
            self._kv_proc = None  # stop supervising the corpse (the
            # degraded flag + kvd_up 0 carry the signal from here)
            return False
        old_id = getattr(self, "_kv_service_id", None)
        if old_id:
            self.meta.update_service(old_id,
                                     status=ServiceStatus.CRASHED)
        log.warning("kvd data plane died (%s): respawning on %s:%d "
                    "with WAL replay from %s", reason, host, port,
                    data_dir)
        fsync = os.environ.get("RAFIKI_KVD_FSYNC",
                               self.KVD_FSYNC_DEFAULT)
        t0 = time.monotonic()
        # ONE boot attempt per monitor tick: poll() holds op_lock, and
        # an in-line wait-for-the-port retry loop here would stall
        # every admin operation for its duration. A failed attempt
        # leaves the dead handle in place so the NEXT poll retries;
        # ~20 ticks of failures (a port that never frees, a corrupt
        # dir the budget check didn't see) go degraded-loud instead.
        try:
            self.kv_host, self.kv_port = "", 0  # let boot re-record
            self._boot_data_plane(host, port, data_dir, fsync)
        except (OSError, RuntimeError) as e:
            self.kv_host, self.kv_port = host, port
            self._kv_boot_attempts += 1
            if self._kv_boot_attempts >= 20:
                self._degraded["data-plane"] = \
                    f"kvd respawn failed: {e}"
                self._kv_proc = None  # see budget branch above
                log.error("kvd respawn failed %d times, giving up: "
                          "%s", self._kv_boot_attempts, e)
            else:
                log.warning("kvd respawn attempt %d failed (%s) — "
                            "retrying on the next monitor tick",
                            self._kv_boot_attempts, e)
            return False
        self._kv_boot_attempts = 0
        try:
            self._respawn_counts[lineage] = \
                self.meta.incr_respawn_count(ServiceType.DATA_PLANE,
                                             "kvd")
        except Exception as e:  # noqa: BLE001 — never lose healing to
            # a store hiccup; fall back to the in-memory count
            log.warning("kvd respawn budget write-through failed: %s",
                        e)
            self._respawn_counts[lineage] = \
                self._respawn_counts.get(lineage, 0) + 1
        self.recovery.inc("kvd_respawns")
        self._degraded.pop("data-plane", None)
        log.warning("kvd respawned in %.2fs (pid %d, replay %.3fs)",
                    time.monotonic() - t0, self._kv_proc.pid,
                    float(self.recovery["kvd_replay_seconds"]))
        return True

    def _check_data_plane(self) -> None:
        """Monitor-tick half of kvd supervision: a data-plane process
        that died (kill -9, OOM) is respawned on its recorded port and
        replays its WAL. Runs under op_lock (poll)."""
        if self._kv_proc is None or self.fenced:
            return
        if self._kv_proc.poll() is None:
            return  # alive
        self._respawn_data_plane(
            f"process exited rc={self._kv_proc.returncode}")

    def _fresh_kvd_stats(self) -> Dict[str, Any]:
        from ..native.client import KVClient

        # op_timeout bounds the read too: a wedged (or compaction-busy)
        # kvd must surface as a caught timeout, not hang every /metrics
        # and /health behind _kvd_stats_lock
        c = KVClient(self.kv_host, self.kv_port, connect_timeout=2.0,
                     op_timeout_s=2.0)
        try:
            return c.stats()
        finally:
            c.close()

    def kvd_stats(self, max_age_s: float = 2.0) -> Dict[str, Any]:
        """Cached kvd STATS (persistence health: wal_bytes,
        snapshot_age_s, last_fsync_age_s, ...) plus ``up``. Guarded by
        its own lock and cached so /metrics scrapes cost at most one
        socket round-trip per ``max_age_s``."""
        with self._kvd_stats_lock:
            now = time.monotonic()
            if now - self._kvd_stats_at < max_age_s:
                return dict(self._kvd_stats_cache)
            if not self.kv_port:
                self._kvd_stats_cache = {"up": 0}
            else:
                try:
                    st = self._fresh_kvd_stats()
                    st["up"] = 1
                    self._kvd_stats_cache = st
                except (OSError, RuntimeError) as e:
                    import logging

                    logging.getLogger(__name__).debug(
                        "kvd stats probe failed: %s", e)
                    self._kvd_stats_cache = {"up": 0}
            self._kvd_stats_at = now
            return dict(self._kvd_stats_cache)

    def kvd_metrics(self) -> Dict[str, Any]:
        """Numeric re-export for the admin /metrics collector:
        ``kvd_up``, ``kvd_wal_bytes``, ``kvd_snapshot_age_s``,
        ``kvd_last_fsync_age_s``, ``kvd_replay_seconds``,
        ``kvd_respawns``."""
        st = self.kvd_stats()
        out = {"kvd_up": int(st.get("up") or 0),
               "kvd_respawns": self.recovery["kvd_respawns"],
               "kvd_replay_seconds":
                   self.recovery["kvd_replay_seconds"]}
        for k in ("wal_bytes", "snapshot_bytes", "snapshot_age_s",
                  "last_fsync_age_s", "compactions",
                  "wal_truncated_bytes"):
            if k in st:
                out[f"kvd_{k}"] = st[k]
        return out

    def data_plane_status(self) -> Dict[str, Any]:
        """The /health ``data_plane`` block: up/down, address, data
        dir, respawn + replay counters, and the persistence stats."""
        st = self.kvd_stats()
        return {"up": bool(st.get("up")),
                "host": self.kv_host, "port": self.kv_port,
                "data_dir": self._kv_data_dir,
                "respawns": self.recovery["kvd_respawns"],
                "replay_seconds":
                    self.recovery["kvd_replay_seconds"],
                "stats": {k: v for k, v in st.items() if k != "up"}}

    @property
    def param_store_uri(self) -> str:
        if self.kv_port:
            return f"kv://{self.kv_host}:{self.kv_port}"
        return f"file://{self.workdir / 'params'}"

    # ---- process plumbing ----
    def _spawn(self, module: str, config: Dict[str, Any],
               service_type: str, slot: Optional[SubMesh] = None,
               wait_port_file: bool = False, timeout: float = 180.0,
               **meta_kwargs: Any) -> ManagedService:
        self._check_fence()
        tag = f"{service_type.lower()}-{uuid.uuid4().hex[:8]}"
        cfg_path = self.workdir / f"{tag}.json"
        port_file = self.workdir / f"{tag}.port"
        if wait_port_file:
            config = {**config, "port_file": str(port_file)}
        cfg_path.write_text(json.dumps(config))

        env = os.environ.copy()
        if slot is not None:
            env.update(submesh_env_vars(self.platform, slot))
        else:
            # control-plane children (advisor/predictor) must never claim
            # accelerator chips — pin them to host CPU
            env.update({"JAX_PLATFORMS": "cpu",
                        "RAFIKI_JAX_PLATFORM": "cpu"})
        log = open(self.workdir / f"{tag}.log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", module, "--config", str(cfg_path)],
            env=env, stdout=log, stderr=subprocess.STDOUT)
        log.close()

        host, port = "127.0.0.1", 0
        if wait_port_file:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if port_file.exists() and port_file.read_text().strip():
                    port = int(port_file.read_text().strip())
                    break
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"{service_type} died on startup; see "
                        f"{self.workdir / f'{tag}.log'}")
                time.sleep(0.05)
            else:
                proc.kill()
                raise TimeoutError(f"{service_type} did not report a port")

        # the ROW carries everything needed to re-adopt or respawn this
        # service after an admin crash: the full spawn recipe plus the
        # pid's kernel start time (the recycle-proof identity half)
        spawn_spec = {"module": module, "config": dict(config),
                      "service_type": service_type,
                      "needs_slot": slot is not None,
                      "meta_kwargs": dict(meta_kwargs), "tag": tag}
        row = self.meta.create_service(
            service_type, host=host, port=port, pid=proc.pid,
            devices=[d.id for d in (slot.devices if slot else [])],
            spawn_spec=spawn_spec,
            start_time=proc_start_time(proc.pid),
            **meta_kwargs)
        svc = ManagedService(row["id"], service_type, proc, slot, host, port)
        self.services[row["id"]] = svc
        if service_type in _WORKER_TYPES:
            self._respawn_specs[row["id"]] = {
                "module": module, "config": dict(config),
                "service_type": service_type, "needs_slot": slot is not None,
                "meta_kwargs": dict(meta_kwargs)}
        self.meta.update_service(row["id"], status=ServiceStatus.RUNNING)
        return svc

    # ---- train jobs (SURVEY.md §3.1) ----
    def create_train_services(self, train_job_id: str,
                              n_workers: Optional[int] = None
                              ) -> List[ManagedService]:
        with self.op_lock:
            # op_lock serializes admin ops end-to-end, spawn port-waits
            # included (see docs/linting.md "Admin op serialization")
            return self._create_train_services(  # rafiki: noqa[lock-order-cycle]
                train_job_id,
                self.default_workers if n_workers is None else n_workers)

    def _create_train_services(self, train_job_id: str,
                               n_workers: int) -> List[ManagedService]:
        job = self.meta.get_train_job(train_job_id)
        if job is None:
            raise KeyError(f"no train job {train_job_id!r}")
        budget = job["budget"]
        n_workers = int(budget.get("WORKER_COUNT",
                                   budget.get("GPU_COUNT", n_workers)))
        subs = self.meta.get_sub_train_jobs_of_train_job(train_job_id)

        # a knob_overrides key that matches NO model's knob config is a
        # typo: fail before spawning anything rather than silently running
        # the full search on the dimension the user believes is pinned
        # (same validator as tune_model's dev loop — model/knob.py)
        requested = job["train_args"].get("knob_overrides") or {}
        if requested:
            from ..model.base import load_model_class
            from ..model.knob import validate_override_keys

            known: set = set()
            for sub in subs:
                model = self.meta.get_model(sub["model_id"])
                known |= set(load_model_class(
                    model["model_bytes"],
                    model["model_class"]).get_knob_config())
            validate_override_keys(
                known, requested,
                context="knob_overrides for this job's models:")

        spawned: List[ManagedService] = []
        for sub in subs:
            model = self.meta.get_model(sub["model_id"])
            model_file = self.workdir / f"model-{model['id']}.py"
            model_file.write_bytes(model["model_bytes"])

            # one advisor service per sub-train-job (reference: one advisor
            # container per model under tuning)
            from ..model.base import load_model_class
            from ..model.knob import knob_config_to_json

            model_class = load_model_class(model["model_bytes"],
                                           model["model_class"])
            knob_config = model_class.get_knob_config()
            # job-level knob pins: keep only the knobs THIS model has
            # (multi-model jobs — other models' knobs must not leak into
            # its proposals) and substitute FixedKnob into the advisor's
            # search space so no trial budget is spent re-sampling pinned
            # dimensions. The worker still merges the same values as a
            # belt-and-braces.
            overrides = {
                k: v for k, v in (job["train_args"].get("knob_overrides")
                                  or {}).items() if k in knob_config}
            if overrides:
                from ..model.knob import FixedKnob

                knob_config = {
                    name: (FixedKnob(overrides[name])
                           if name in overrides else knob)
                    for name, knob in knob_config.items()}
            advisor = self._spawn(
                "rafiki_tpu.advisor.service",
                {"knob_config": knob_config_to_json(knob_config),
                 "advisor_type": job["train_args"].get("advisor", "auto"),
                 "total_trials": budget.get("TRIAL_COUNT"),
                 "time_budget_s": (float(budget["TIME_HOURS"]) * 3600
                                   if budget.get("TIME_HOURS") else None)},
                ServiceType.ADVISOR, wait_port_file=True,
                train_job_id=train_job_id, sub_train_job_id=sub["id"])
            spawned.append(advisor)

            # per-trial jax.profiler traces, opt-in via train_args
            profile_dir = ""
            if job["train_args"].get("profile"):
                profile_dir = str(self.workdir / "profiles" / sub["id"])
            for w in range(n_workers):
                slot = self.allocator.acquire(timeout=0.0)
                if slot is None:
                    break  # no free sub-mesh; trials queue on fewer workers
                try:
                    worker = self._spawn(
                        "rafiki_tpu.worker.train",
                        {"advisor_url": advisor.url,
                         "model_file": str(model_file),
                         "model_class": model["model_class"],
                         "model_id": model["id"],
                         "train_dataset": job["train_dataset_id"],
                         "val_dataset": job["val_dataset_id"],
                         "param_store_uri": self.param_store_uri,
                         "meta_store_path": self.meta._db_path,
                         "sub_train_job_id": sub["id"],
                         "profile_dir": profile_dir,
                         "knob_overrides": overrides,
                         # gang trial mode: K trials per compiled step
                         # on this worker's sub-mesh (small-zoo
                         # templates)
                         "gang_size": int(job["train_args"].get(
                             "gang_size") or 0),
                         "checkpoint_interval_s": job["train_args"].get(
                             "checkpoint_interval_s", 30.0),
                         "worker_id": f"tw-{sub['id'][:8]}-{w}",
                         # /metrics + /debug/requests sidecar:
                         # ephemeral port, discoverable from this file
                         "obs_port_file": str(
                             self.workdir / f"tw-{sub['id'][:8]}-{w}"
                                            ".obs_port")},
                        ServiceType.TRAIN_WORKER, slot=slot,
                        train_job_id=train_job_id,
                        sub_train_job_id=sub["id"])
                except Exception:
                    # the slot was never handed to a live service:
                    # return it to the pool or it is gone until admin
                    # restart (every sibling spawn site guards this)
                    self.allocator.release(slot)
                    raise
                spawned.append(worker)
            self.meta.update_sub_train_job(
                sub["id"], status=SubTrainJobStatus.RUNNING)
        self.meta.update_train_job(train_job_id,
                                   status=TrainJobStatus.RUNNING)
        return spawned

    def wait_train_job(self, train_job_id: str,
                       timeout: float = 3600.0) -> bool:
        """Block until every train worker of the job exits; stops the
        job's advisors; returns True if it finished in time."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.poll()
            # re-list each tick: poll() may have RESPAWNED a crashed
            # worker — a snapshot would declare the job done while the
            # replacement is still training. A queued (slot-starved)
            # respawn also keeps the job busy.
            workers = [s for s in self.services.values()
                       if s.service_type == ServiceType.TRAIN_WORKER]
            if all(not s.alive() for s in workers) and \
                    train_job_id not in self.pending_respawn_job_ids():
                break
            time.sleep(0.2)
        else:
            return False
        for s in list(self.services.values()):
            if s.service_type == ServiceType.ADVISOR:
                self.stop_service(s.service_id)
        for sub in self.meta.get_sub_train_jobs_of_train_job(train_job_id):
            self.meta.update_sub_train_job(sub["id"],
                                           status=SubTrainJobStatus.STOPPED)
        self.meta.update_train_job(train_job_id,
                                   status=TrainJobStatus.STOPPED)
        return True

    # ---- inference jobs (SURVEY.md §3.2) ----
    def create_inference_services(self, inference_job_id: str,
                                  max_workers: int = 2
                                  ) -> List[ManagedService]:
        ijob = self.meta.get_inference_job(inference_job_id)
        if ijob is None:
            raise KeyError(f"no inference job {inference_job_id!r}")
        best = self.meta.get_best_trials_of_train_job(
            ijob["train_job_id"], max_count=max_workers)
        if not best:
            raise RuntimeError("no completed trials to deploy")
        # MULTI_ADAPTER budget flag: deploy the best-N LM trials as ONE
        # worker serving N stacked LoRA adapters (adapter 0 = best
        # trial, i = i-th best; requests route via sampling
        # {"adapter_id": i}) instead of N full replicas — one base
        # model's HBM, one device slot. Requires adapters_only trials;
        # a mismatched base fails the worker boot loudly. Best trials
        # can span MODELS (a train job tunes every registered template
        # for its task), so extras are filtered to the primary trial's
        # model — a foreign trial's dump can't stack onto its base.
        budget = ijob.get("budget") or {}
        multi_adapter = False
        if bool(budget.get("MULTI_ADAPTER")) and len(best) > 1:
            import logging

            log = logging.getLogger(__name__)

            def model_of(trial):
                sub = self.meta.get_sub_train_job(
                    trial["sub_train_job_id"])
                return self.meta.get_model(sub["model_id"])

            primary_model = model_of(best[0])
            if primary_model["task"] != TaskType.LANGUAGE_MODELING:
                log.warning(
                    "MULTI_ADAPTER ignored: task %s is not a language-"
                    "modeling job; deploying plain replicas",
                    primary_model["task"])
            else:
                # stackable = same model AND same shape signature as
                # the primary (shape-relevant knobs are advisor-
                # searched, so same-model trials can still disagree on
                # hidden_dim/rank/...; shipping those to one engine
                # would be a guaranteed crash-looping worker boot)
                sig0 = best[0].get("shape_signature")
                same = [best[0]] + [
                    t for t in best[1:]
                    if model_of(t)["id"] == primary_model["id"]
                    and t.get("shape_signature") == sig0]
                if len(same) > 1:
                    if len(same) < len(best):
                        log.warning(
                            "MULTI_ADAPTER: dropping %d best trial(s) "
                            "with a different model or shape; stacking "
                            "%d trials of model %s",
                            len(best) - len(same), len(same),
                            primary_model["id"])
                    best = same
                    multi_adapter = True
                else:
                    log.warning(
                        "MULTI_ADAPTER ignored: no other best trial "
                        "shares model %s and shape %r; deploying "
                        "plain replicas", primary_model["id"], sig0)
        n_services = 1 if multi_adapter else len(best)

        # autoscale bounds validate at the API surface — a bad bound
        # (MIN > initial, MAX < MIN, bounds without AUTOSCALE) fails
        # the create call, not a monitor tick hours later
        if AutoscaleConfig.from_budget(budget, n_services) is not None \
                and n_services > 1:
            # replicas deploy DISTINCT best trials (an ensemble);
            # autoscaled clones of trial 0 would double-weight it in
            # the unary gather, and a scale-down could evict another
            # trial's only replica
            raise ValueError(
                "AUTOSCALE requires a single-replica deployment "
                f"(this create would spawn {n_services} workers, one "
                "per DISTINCT best trial): create with max_workers=1 "
                "(or MULTI_ADAPTER) and let the autoscaler grow the "
                "pool with clones of the best trial")

        # A replica MUST own a device slot: quietly pinning it to host CPU
        # would serve at CPU speed — a perf cliff, never a default. Acquire
        # every slot BEFORE taking op_lock: release paths (poll /
        # stop_service) need that lock, so blocking on the allocator while
        # holding it could never be satisfied by a concurrent release.
        slots: List[SubMesh] = []
        for i in range(n_services):
            slot = self.allocator.acquire(timeout=self.slot_timeout)
            if slot is None:
                for s in slots:
                    self.allocator.release(s)
                self.meta.update_inference_job(inference_job_id,
                                               status="ERRORED")
                raise RuntimeError(
                    f"no free device slot for inference replica {i} after "
                    f"{self.slot_timeout:.0f}s ({self.allocator.n_slots} "
                    f"slots, {self.allocator.free_count()} free); stop a "
                    "running job or lower the replica count")
            slots.append(slot)

        with self.op_lock:
            try:
                # op_lock serializes admin ops end-to-end, spawn
                # port-waits included (see docs/linting.md "Admin op
                # serialization")
                return self._create_inference_services(  # rafiki: noqa[lock-order-cycle]
                    inference_job_id, best, slots,
                    multi_adapter=multi_adapter)
            except BaseException:
                # slots not yet handed to a spawned service stay ours —
                # give them back (spawned services release via _poll/stop)
                held = {id(s.slot) for s in self.services.values()
                        if s.slot is not None}
                for slot in slots:
                    if id(slot) not in held:
                        try:
                            self.allocator.release(slot)
                        except ValueError:
                            pass  # already released by a service stop
                self.meta.update_inference_job(inference_job_id,
                                               status="ERRORED")
                raise

    def _create_inference_services(self, inference_job_id: str,
                                   best: List[Dict[str, Any]],
                                   slots: List["SubMesh"],
                                   multi_adapter: bool = False
                                   ) -> List[ManagedService]:
        if not self.kv_port:
            self.start_data_plane()

        ijob = self.meta.get_inference_job(inference_job_id) or {}
        budget = ijob.get("budget") or {}
        spawned: List[ManagedService] = []
        worker_ids: List[str] = []
        services = [best[0]] if multi_adapter else best
        # SLO / overload budget keys, validated HERE at the create API
        # (a typo'd class or negative cap fails the call, not a
        # crash-looping worker). SLO_DEFAULT classes unlabeled
        # requests on the predictor AND every worker; SLO_P95_TARGET_S
        # (> 0, seconds of interactive TTFT p95) arms the predictor's
        # brownout ladder; SLO_SHED_BATCH_DEPTH /
        # SLO_SHED_BACKGROUND_DEPTH (>= 0) cap best-effort backlog;
        # SLO_BACKGROUND_MAX_NEW (>= 1) is the ladder's stage-2 clamp
        # and therefore requires the ladder to be armed.
        from ..serving.slo import normalize_slo
        slo_default = ""
        if "SLO_DEFAULT" in budget:
            try:
                slo_default = normalize_slo(budget["SLO_DEFAULT"])
            except ValueError as e:
                raise ValueError(f"SLO_DEFAULT: {e}") from e
        slo_shed_depths: Dict[str, int] = {}
        for key, cls in (("SLO_SHED_BATCH_DEPTH", "batch"),
                         ("SLO_SHED_BACKGROUND_DEPTH", "background")):
            if key in budget:
                d = int(budget[key])
                if d < 0:
                    raise ValueError(f"{key}={d} must be >= 0 "
                                     "(fleet queue-backlog cap)")
                slo_shed_depths[cls] = d
        brownout_target = 0.0
        if budget.get("SLO_P95_TARGET_S"):
            brownout_target = float(budget["SLO_P95_TARGET_S"])
            if brownout_target <= 0:
                raise ValueError(
                    f"SLO_P95_TARGET_S={budget['SLO_P95_TARGET_S']} "
                    "must be > 0 (target interactive TTFT p95, "
                    "seconds)")
        # Disaggregated prefill/decode + host KV tier budget keys,
        # validated HERE at the create API like every serving knob.
        # WORKER_ROLE: one role broadcast to every worker, or a
        # comma-separated role per worker index ("prefill,decode,
        # decode") — any prefill role requires at least one serving
        # (decode/unified) role or nothing would answer queries.
        # HOST_KV_PAGES (>= 1, requires KV_PAGE_SIZE): pinned-host KV
        # page tier per worker — admission budget becomes HBM + host.
        # KV_WAIT_S (>= 0): how long a decode worker holds a request
        # for its KV shipment before re-prefilling locally.
        from ..serving.kv_transfer import normalize_role
        roles: List[str] = []
        if budget.get("WORKER_ROLE"):
            try:
                roles = [normalize_role(r) for r in
                         str(budget["WORKER_ROLE"]).split(",")]
            except ValueError as e:
                raise ValueError(f"WORKER_ROLE: {e}") from e
            if len(roles) == 1:
                roles = roles * len(services)
            if len(roles) != len(services):
                raise ValueError(
                    f"WORKER_ROLE names {len(roles)} roles for "
                    f"{len(services)} workers (one per worker, or a "
                    "single role for all)")
            if any(r == "prefill" for r in roles) and \
                    all(r == "prefill" for r in roles):
                raise ValueError(
                    "WORKER_ROLE: an all-prefill pool serves nothing "
                    "— at least one worker must be decode or unified")
        host_kv_pages = 0
        if budget.get("HOST_KV_PAGES"):
            host_kv_pages = int(budget["HOST_KV_PAGES"])
            if host_kv_pages < 1:
                raise ValueError(
                    f"HOST_KV_PAGES={host_kv_pages} must be >= 1 "
                    "(host-tier page count)")
            if not budget.get("KV_PAGE_SIZE"):
                raise ValueError(
                    "HOST_KV_PAGES requires KV_PAGE_SIZE in the same "
                    "budget (pages are the host tier's transfer unit)")
        kv_wait_s = None
        if "KV_WAIT_S" in budget:
            kv_wait_s = float(budget["KV_WAIT_S"])
            if kv_wait_s < 0:
                raise ValueError(f"KV_WAIT_S={kv_wait_s} must be >= 0")
            if not roles:
                raise ValueError(
                    "KV_WAIT_S requires WORKER_ROLE in the same "
                    "budget (it tunes the disaggregated decode leg)")
        bg_clamp = 0
        if "SLO_BACKGROUND_MAX_NEW" in budget:
            # membership, not truthiness: 0 must FAIL the create call
            # (the documented >= 1 contract), not silently fall back
            # to the predictor's default clamp
            bg_clamp = int(budget["SLO_BACKGROUND_MAX_NEW"])
            if bg_clamp < 1:
                raise ValueError(
                    f"SLO_BACKGROUND_MAX_NEW={bg_clamp} must be >= 1")
            if not brownout_target:
                raise ValueError(
                    "SLO_BACKGROUND_MAX_NEW requires SLO_P95_TARGET_S "
                    "in the same budget (the brownout ladder applies "
                    "the clamp at stage 2)")
        for i, trial in enumerate(services):
            sub = self.meta.get_sub_train_job(trial["sub_train_job_id"])
            model = self.meta.get_model(sub["model_id"])
            model_file = self.workdir / f"model-{model['id']}.py"
            model_file.write_bytes(model["model_bytes"])
            wid = f"iw-{inference_job_id[:8]}-{i}"
            slot = slots[i]
            # generative tasks serve through the continuous-batching
            # decode loop (slot-based KV admission) instead of the
            # classification micro-batcher
            decode_loop = model["task"] == TaskType.LANGUAGE_MODELING
            cfg = {"model_file": str(model_file),
                   "model_class": model["model_class"],
                   "trial_id": trial["id"], "knobs": trial["knobs"],
                   "param_store_uri": self.param_store_uri,
                   "kv_host": self.kv_host, "kv_port": self.kv_port,
                   "worker_id": wid, "decode_loop": decode_loop,
                   # /metrics + /debug/requests sidecar: ephemeral
                   # port, discoverable from this file (and from the
                   # obs_port gauge the worker publishes to /health)
                   "obs_port_file": str(self.workdir
                                        / f"{wid}.obs_port"),
                   # decode-loop dispatch amortization (ops guide): K
                   # fused steps per device program, tunable per job
                   "steps_per_sync": int(budget.get("STEPS_PER_SYNC",
                                                    4))}
            if budget.get("MAX_NEW_TOKENS"):
                cfg["max_new_tokens"] = int(budget["MAX_NEW_TOKENS"])
            if slo_default:
                cfg["default_slo"] = slo_default
            if budget.get("SYSTEM_PREFIX"):
                cfg["system_prefix"] = str(budget["SYSTEM_PREFIX"])
            if budget.get("KV_PAGE_SIZE"):
                # paged (block-table) KV serving: cache HBM and
                # admission scale with the page pool (live tokens),
                # not max_slots x max_len — see docs/operations.md
                # "Paged KV cache". KV_PAGES sizes the pool (0/unset =
                # full coverage, no saving). Misconfigurations fail
                # HERE at the API call, not as a crash-looping worker.
                if not decode_loop:
                    raise ValueError(
                        "KV_PAGE_SIZE requires a language-modeling "
                        "deployment (the decode loop owns the KV "
                        f"cache); task {model['task']} serves through "
                        "the micro-batcher")
                page = int(budget["KV_PAGE_SIZE"])
                trial_max_len = int(
                    (trial.get("knobs") or {}).get("max_len", 0) or 0)
                if page <= 0 or (trial_max_len
                                 and trial_max_len % page):
                    # the engine's own validity rule, enforced at the
                    # deployment surface (a bad page size would
                    # otherwise kill the worker at engine build)
                    raise ValueError(
                        f"KV_PAGE_SIZE={page} must be > 0 and divide "
                        f"the trial's max_len ({trial_max_len})")
                cfg["kv_page_size"] = page
                if budget.get("KV_PAGES"):
                    pages = int(budget["KV_PAGES"])
                    if pages < 2:
                        raise ValueError(
                            f"KV_PAGES={pages} must be >= 2 (page 0 "
                            "is the scratch page; at least one usable "
                            "page) — omit it for the full-coverage "
                            "default")
                    cfg["kv_pages"] = pages
                if "PAGED_KERNEL" in budget:
                    # paged decode dispatch override: the Pallas
                    # block-table kernel vs the page gather. Unset /
                    # blank / "auto" keep the ops-level rule (kernel
                    # on TPU, gather off-TPU); an explicit value
                    # forces one path fleet-wide for this job (A/B,
                    # incident rollback). Parsed by the WORKER'S own
                    # tri-state coercion so the admin surface can
                    # never mean something different from the same
                    # value in a worker config.
                    from ..worker.inference import _tristate

                    pk = _tristate(budget["PAGED_KERNEL"])
                    if pk is not None:
                        cfg["paged_kernel"] = pk
            elif budget.get("KV_PAGES"):
                raise ValueError(
                    "KV_PAGES requires KV_PAGE_SIZE in the same "
                    "budget (pages have no size without it)")
            elif "PAGED_KERNEL" in budget:
                raise ValueError(
                    "PAGED_KERNEL requires KV_PAGE_SIZE in the same "
                    "budget (it selects the PAGED decode path's "
                    "implementation)")
            if host_kv_pages:
                # KV_PAGE_SIZE validation above already guaranteed the
                # decode loop and a paged engine
                cfg["host_kv_pages"] = host_kv_pages
            if roles:
                if not decode_loop:
                    raise ValueError(
                        "WORKER_ROLE requires a language-modeling "
                        "deployment (the decode loop owns the KV "
                        f"shipments); task {model['task']} serves "
                        "through the micro-batcher")
                if roles[i] != "unified":
                    cfg["role"] = roles[i]
            if kv_wait_s is not None:
                cfg["kv_wait_s"] = kv_wait_s
            # the job's pool id keys cross-worker shared state (the
            # prefix-snapshot blob): one replica prefills the shared
            # prefix, every peer imports it
            cfg["pool_id"] = inference_job_id
            if decode_loop and budget.get("SPECULATE_K"):
                # speculative decoding at the DEPLOYMENT surface:
                # SPECULATE_K alone enables prompt-lookup drafting;
                # DRAFT_TRIAL_ID names a (smaller) completed trial as
                # the draft MODEL. The draft must be the same template
                # (the engine's vocab check guards the rest); its own
                # trial knobs shape it. Misconfigurations fail HERE at
                # the API call, not as a crash-looping worker boot.
                spec_k = int(budget["SPECULATE_K"])
                if spec_k < 2:
                    raise ValueError(
                        f"SPECULATE_K={spec_k} must be >= 2 (draft "
                        "window depth; 1 would verify nothing)")
                cfg["speculate_k"] = spec_k
                draft_id = str(budget.get("DRAFT_TRIAL_ID") or "")
                if draft_id:
                    d_trial = self.meta.get_trial(draft_id)
                    if d_trial is None:
                        raise KeyError(
                            f"DRAFT_TRIAL_ID {draft_id!r} names no "
                            "trial")
                    d_sub = self.meta.get_sub_train_job(
                        d_trial["sub_train_job_id"])
                    if d_sub and d_sub["model_id"] != model["id"]:
                        raise ValueError(
                            f"DRAFT_TRIAL_ID {draft_id!r} is a "
                            f"different model ({d_sub['model_id']}) "
                            f"than the deployed {model['id']} — the "
                            "draft must share the target's template/"
                            "tokenizer")
                    cfg["draft_trial_id"] = draft_id
                    cfg["draft_knobs"] = d_trial["knobs"]
            elif budget.get("DRAFT_TRIAL_ID") or budget.get(
                    "SPECULATE_K"):
                if not decode_loop:
                    raise ValueError(
                        "SPECULATE_K/DRAFT_TRIAL_ID require a "
                        "language-modeling deployment (the decode "
                        f"loop); task {model['task']} serves through "
                        "the micro-batcher")
                raise ValueError(
                    "DRAFT_TRIAL_ID requires SPECULATE_K >= 2 (the "
                    "draft window depth) in the same budget")
            if multi_adapter:
                # the other best trials ride as stacked adapters 1..N
                cfg["extra_adapter_trials"] = [t["id"]
                                               for t in best[1:]]
            svc = self._spawn(
                "rafiki_tpu.worker.inference", cfg,
                ServiceType.INFERENCE_WORKER, slot=slot,
                inference_job_id=inference_job_id)
            spawned.append(svc)
            worker_ids.append(wid)

        pred_cfg: Dict[str, Any] = {
            "worker_ids": worker_ids, "kv_host": self.kv_host,
            "kv_port": self.kv_port, "host": "127.0.0.1", "port": 0,
            # live routing-pool membership key: the predictor's
            # router/breaker tables follow autoscale events published
            # under the job id without a predictor rebuild
            "pool_id": inference_job_id,
            # the serving latency/accuracy controller (paper's
            # batching/wait tradeoff): gather deadline tracks the
            # fleet's observed reply latencies instead of always
            # waiting full timeout for stragglers
            "adaptive_gather": bool(budget.get("ADAPTIVE_GATHER"))}
        if slo_default:
            pred_cfg["default_slo"] = slo_default
        if slo_shed_depths:
            pred_cfg["slo_shed_depths"] = slo_shed_depths
        if brownout_target:
            pred_cfg["brownout_target_p95_s"] = brownout_target
        if bg_clamp:
            pred_cfg["brownout_clamp_max_new"] = bg_clamp
        predictor = self._spawn(
            "rafiki_tpu.serving.predictor", pred_cfg,
            ServiceType.PREDICTOR, wait_port_file=True,
            inference_job_id=inference_job_id)
        spawned.append(predictor)
        self.meta.update_inference_job(
            inference_job_id, status="RUNNING",
            predictor_host=f"{predictor.host}:{predictor.port}")
        # arm the scale-out state (routing pool + replica template +
        # autoscale policy when the budget asked for one) and publish
        # the initial membership for the predictor's router
        self._ensure_scaleout(inference_job_id)
        self._publish_pool(inference_job_id)
        return spawned

    # ---- lifecycle / failure detection ----
    def poll(self) -> None:
        """Reap exited children; release their slots; record status."""
        with self.op_lock:
            # op_lock serializes admin ops end-to-end; _poll's respawn
            # path waits on spawn port files by design (see
            # docs/linting.md "Admin op serialization")
            self._poll()  # rafiki: noqa[lock-order-cycle]

    def _poll(self) -> None:
        self._check_data_plane()
        if self._pending_respawns:
            still_pending: List[Dict[str, Any]] = []
            for item in self._pending_respawns:
                try:
                    if not self._respawn(item["dead_id"], item["spec"]):
                        still_pending.append(item)
                except Exception as e:  # noqa: BLE001 — keep polling,
                    import logging      # but never drop healing silently

                    logging.getLogger(__name__).warning(
                        "queued respawn for %s failed and was dropped: "
                        "%s", item["dead_id"], e)
                    mk = item["spec"]["meta_kwargs"]
                    self._mark_degraded(
                        item["spec"]["service_type"],
                        mk.get("train_job_id")
                        or mk.get("inference_job_id"),
                        f"queued respawn failed: {e}")
            self._pending_respawns = still_pending
        for svc in list(self.services.values()):
            if svc.alive():
                continue
            code = svc.proc.returncode
            status = (ServiceStatus.STOPPED if code == 0
                      else ServiceStatus.ERRORED)
            self.meta.update_service(svc.service_id, status=status)
            if svc.slot is not None:
                self.allocator.release(svc.slot)
                svc.slot = None
            spec = self._respawn_specs.pop(svc.service_id, None)
            del self.services[svc.service_id]
            if status == ServiceStatus.ERRORED and spec is not None:
                # self-healing: a CRASHED worker is replaced while its
                # job still runs (rc==0 = normal completion, no respawn).
                # Train-worker replacements then reclaim the dead
                # process's orphaned trial via the resume machinery.
                try:
                    if not self._respawn(svc.service_id, spec):
                        # no free slot this instant (a concurrent spawn
                        # may have snatched the released one): retry on
                        # subsequent polls rather than losing healing
                        self._pending_respawns.append(
                            {"dead_id": svc.service_id, "spec": spec})
                except Exception as e:  # noqa: BLE001 — the monitor loop
                    import logging     # must survive respawn failures

                    logging.getLogger(__name__).warning(
                        "respawn of %s failed: %s", svc.service_id, e)

    def _respawn(self, dead_service_id: str, spec: Dict[str, Any]) -> bool:
        """Spawn a replacement for a crashed worker. Returns True when
        the case is RESOLVED (respawned, or no longer needed); False =
        no free slot right now, caller should queue a retry."""
        meta_kwargs = spec["meta_kwargs"]
        job_id = meta_kwargs.get("train_job_id") or \
            meta_kwargs.get("inference_job_id")
        stype = spec["service_type"]
        if stype == ServiceType.TRAIN_WORKER:
            job = self.meta.get_train_job(job_id) if job_id else None
        else:
            job = self.meta.get_inference_job(job_id) if job_id else None
        if not job or job["status"] != "RUNNING":
            return True  # parent finished/stopped: nothing to heal
        lineage = (stype, job_id)
        if self._respawn_counts.get(lineage, 0) >= self.max_respawns:
            import logging

            logging.getLogger(__name__).warning(
                "respawn budget exhausted for %s job %s (last casualty "
                "%s) — a worker config appears to crash "
                "deterministically", stype, job_id, dead_service_id)
            # the drop is not just a log line: the job surfaces as
            # degraded on /health (and ERRORED in the store when it has
            # no workers left at all)
            self._mark_degraded(stype, job_id,
                                "respawn budget exhausted")
            return True
        slot = None
        if spec["needs_slot"]:
            slot = self.allocator.acquire(timeout=0.0)
            if slot is None:
                return False  # no free chips; caller queues a retry
        try:
            self._spawn(spec["module"], spec["config"], stype, slot=slot,
                        **meta_kwargs)
        except Exception:
            if slot is not None:
                self.allocator.release(slot)
            raise
        # write-through: the budget lives in the MetaStore so an admin
        # crash cannot reset it (a crash-looping worker config would
        # otherwise get a fresh budget per admin restart)
        try:
            self._respawn_counts[lineage] = \
                self.meta.incr_respawn_count(stype, job_id)
        except Exception:  # noqa: BLE001 — never lose healing to a
            # store hiccup; fall back to the in-memory count
            self._respawn_counts[lineage] = \
                self._respawn_counts.get(lineage, 0) + 1
        # healing worked: the job is no longer degraded (a stale flag
        # that survives recovery teaches operators to ignore it)
        self._degraded.pop(job_id, None)
        return True

    def _live_workers_of(self, stype: str, job_id: str
                         ) -> List[ManagedService]:
        """Still-alive workers of ``stype`` belonging to ``job_id``
        (caller holds op_lock or tolerates a snapshot)."""
        key = ("train_job_id" if stype == ServiceType.TRAIN_WORKER
               else "inference_job_id")
        out = []
        for sid, svc in self.services.items():
            if svc.service_type != stype or not svc.alive():
                continue
            spec = self._respawn_specs.get(sid)
            if spec and spec["meta_kwargs"].get(key) == job_id:
                out.append(svc)
        return out

    def _mark_degraded(self, stype: str, job_id: Optional[str],
                       reason: str) -> None:
        """Record a job whose self-healing is gone. With zero workers
        left the job is not degraded but DEAD — its store row flips to
        ERRORED so the dashboard's status column shows it."""
        if not job_id:
            return
        self._degraded[job_id] = reason
        if self._live_workers_of(stype, job_id):
            return  # under-replicated but still serving
        import logging

        try:
            if stype == ServiceType.TRAIN_WORKER:
                self.meta.update_train_job(job_id,
                                           status=TrainJobStatus.ERRORED)
            else:
                self.meta.update_inference_job(job_id, status="ERRORED")
        except Exception as e:  # noqa: BLE001 — a store hiccup must not
            # kill the monitor loop; the /health degraded list already
            # carries the signal
            logging.getLogger(__name__).warning(
                "could not mark job %s ERRORED: %s", job_id, e)

    def degraded_jobs(self) -> Dict[str, str]:
        """Jobs that lost self-healing (job id → reason), for /health.
        Jobs an operator has since STOPPED drop off the list (ERRORED
        ones stay — that verdict is the point of the flag)."""
        with self.op_lock:
            out = dict(self._degraded)
        for jid in list(out):
            job = self.meta.get_train_job(jid) or \
                self.meta.get_inference_job(jid)
            if job is not None and job.get("status") == "STOPPED":
                with self.op_lock:
                    self._degraded.pop(jid, None)
                del out[jid]
        return out

    def respawn_stats(self) -> Dict[str, int]:
        """Self-healing counters for /health (locked: the monitor thread
        mutates these dicts while HTTP threads read)."""
        with self.op_lock:
            return {"respawns_done": sum(self._respawn_counts.values()),
                    "pending_respawns": len(self._pending_respawns),
                    "degraded_jobs": len(self._degraded),
                    "rolling_restarts_done": self._rolling_restarts}

    # ---- graceful drain / rolling restart ----
    def _request_drain(self, config: Dict[str, Any]) -> bool:
        """Ask a worker to drain: POST /drain on its obs sidecar
        (discovered via the obs_port_file the worker wrote at boot),
        falling back to a ``{"control": "drain"}`` message on its query
        queue. Returns False when neither channel is available."""
        import logging

        from ..utils.http import json_request

        log = logging.getLogger(__name__)
        port_file = config.get("obs_port_file")
        if port_file:
            try:
                port = int(Path(port_file).read_text().strip())
                json_request("POST", f"http://127.0.0.1:{port}/drain",
                             {}, timeout=5.0)
                return True
            except Exception as e:  # noqa: BLE001 — the sidecar may be
                # gone with a hung worker; the queue channel still works
                log.warning("drain via obs sidecar failed (%s); "
                            "falling back to queue control message", e)
        wid = config.get("worker_id")
        if wid and self.kv_port:
            from ..serving.queues import KVQueueHub, pack_message

            KVQueueHub(self.kv_host, self.kv_port).push_query(
                wid, pack_message({"control": "drain"}))
            return True
        log.warning("no drain channel for worker config %r",
                    config.get("worker_id"))
        return False

    def rolling_restart(self, inference_job_id: str,
                        drain_timeout: float = 120.0
                        ) -> Dict[str, Any]:
        """Drain → stop → respawn each of a live inference job's
        workers ONE AT A TIME, so a deploy/restart never drops a
        stream: the draining worker finishes its in-flight requests
        (streams included) while the predictor's breaker board routes
        new traffic to its siblings; only then is it replaced. A worker
        that fails to drain within ``drain_timeout`` is terminated —
        the restart must converge even over a hung process. Returns the
        old→new service id pairs."""
        self._check_fence()
        if not self._rolling_lock.acquire(blocking=False):
            raise RuntimeError(
                "a rolling restart is already in progress — wait for "
                "it to finish (retrying a timed-out request would "
                "drain the fresh replacements)")
        try:
            return self._rolling_restart(inference_job_id,
                                         drain_timeout)
        finally:
            self._rolling_lock.release()

    def _rolling_restart(self, inference_job_id: str,
                         drain_timeout: float) -> Dict[str, Any]:
        with self.op_lock:
            targets = []
            for sid, svc in list(self.services.items()):
                if svc.service_type != ServiceType.INFERENCE_WORKER:
                    continue
                spec = self._respawn_specs.get(sid)
                if spec and spec["meta_kwargs"].get(
                        "inference_job_id") == inference_job_id:
                    targets.append((sid, svc, spec))
        if not targets:
            raise KeyError("no live inference workers for job "
                           f"{inference_job_id!r}")
        import logging

        log = logging.getLogger(__name__)
        restarted = []
        for sid, svc, spec in targets:
            with self.op_lock:
                # de-register crash healing for THIS worker only, at
                # its own turn: dying non-zero while draining (or the
                # terminate below) must not make the monitor respawn
                # it in parallel with the replacement spawned here —
                # while workers not yet reached keep their healing if
                # the restart aborts mid-way
                self._respawn_specs.pop(sid, None)
            drain_sent = self._request_drain(spec["config"])
            # wait OUTSIDE op_lock: the monitor thread must stay able
            # to poll (and the draining worker may take a while to
            # finish its streams). A worker that was never asked to
            # drain (no channel) gets a short grace, not the full
            # budget — waiting can't help it finish what it doesn't
            # know to finish.
            try:
                svc.proc.wait(timeout=drain_timeout if drain_sent
                              else min(5.0, drain_timeout))
            except subprocess.TimeoutExpired:
                log.warning(
                    "worker %s did not drain within %.0fs%s; "
                    "terminating", sid, drain_timeout,
                    "" if drain_sent else " (no drain channel)")
                svc.proc.terminate()
                try:
                    svc.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    svc.proc.kill()
                    svc.proc.wait()
            with self.op_lock:
                if sid in self.services:  # the monitor may have reaped
                    # the rc=0 exit already (drain = clean completion)
                    self.meta.update_service(sid,
                                             status=ServiceStatus.STOPPED)
                    if svc.slot is not None:
                        self.allocator.release(svc.slot)
                        svc.slot = None
                    self._respawn_specs.pop(sid, None)
                    del self.services[sid]
                slot = None
                if spec["needs_slot"]:
                    slot = self.allocator.acquire(
                        timeout=self.slot_timeout)
                    if slot is None:
                        raise RuntimeError(
                            "no free device slot to respawn drained "
                            f"worker {sid} — rolling restart aborted "
                            "mid-way")
                try:
                    # rolling restart must hold op_lock across the
                    # spawn wait — releasing it mid-restart would let
                    # a concurrent scale op grab the vacated slot (see
                    # docs/linting.md "Admin op serialization")
                    new = self._spawn(spec["module"], spec["config"],  # rafiki: noqa[lock-order-cycle]
                                      spec["service_type"], slot=slot,
                                      **spec["meta_kwargs"])
                except Exception:
                    if slot is not None:
                        self.allocator.release(slot)
                    raise
                self._rolling_restarts += 1
                # a fresh healthy worker supersedes any degraded flag
                self._degraded.pop(inference_job_id, None)
            restarted.append({"old": sid, "new": new.service_id,
                              "drained": bool(drain_sent)})
        return {"job_id": inference_job_id, "restarted": restarted}

    # ---- horizontal scale-out / autoscaler ----
    #: floor between autoscale evaluations (the monitor ticks faster)
    AUTOSCALE_TICK_EVERY_S = 1.0
    #: a scaled-up worker joins the routing pool when its obs sidecar
    #: reports a port (boot + warmup complete) — or after this long
    #: regardless (the predictor's breakers gate a worker that still
    #: is not serving; membership must not hang on a lost port file)
    WARM_PUBLISH_TIMEOUT_S = 600.0

    def _pool_hub(self):
        """A cached KVQueueHub against the live data plane (worker
        stats reads + pool-membership publishes)."""
        from ..serving.queues import KVQueueHub

        key = (self.kv_host, self.kv_port)
        if self._pool_hub_cache is None or self._pool_hub_key != key:
            self._pool_hub_cache = KVQueueHub(self.kv_host, self.kv_port)
            self._pool_hub_key = key
        return self._pool_hub_cache

    @staticmethod
    def _wid_index(wid: str) -> int:
        """The numeric suffix of ``iw-<job8>-<n>`` worker ids (pool
        ordering + next-index recovery); -1 when unparseable."""
        try:
            return int(wid.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return -1

    def _ensure_scaleout(self, job_id: str) -> Optional[Dict[str, Any]]:
        """The job's scale-out state, rebuilt from live services + the
        job budget when missing (an adopted stack keeps scaling after
        an admin restart). None when the job has no live inference
        workers to derive a pool/template from."""
        with self.op_lock:
            st = self._scaleout.get(job_id)
            if st is not None:
                return st
            workers: List[Any] = []
            for sid, spec in self._respawn_specs.items():
                if spec["service_type"] != ServiceType.INFERENCE_WORKER:
                    continue
                if spec["meta_kwargs"].get("inference_job_id") != job_id:
                    continue
                wid = str(spec["config"].get("worker_id") or "")
                if wid:
                    workers.append((self._wid_index(wid), wid, spec))
            if not workers:
                return None
            workers.sort(key=lambda t: (t[0], t[1]))
            job = self.meta.get_inference_job(job_id)
            budget = (job or {}).get("budget") or {}
            policy = None
            trial_ids = {s["config"].get("trial_id")
                         for _, _, s in workers}
            try:
                cfg_as = AutoscaleConfig.from_budget(budget,
                                                     len(workers))
                if cfg_as is not None and len(trial_ids) > 1:
                    # an ensemble pool (distinct trials) must never be
                    # auto-scaled: clones would skew the gather and a
                    # shrink could evict a trial's only replica
                    raise ValueError(
                        "pool serves distinct trials (ensemble)")
                if cfg_as is not None:
                    policy = AutoscalePolicy(cfg_as)
            except ValueError as e:
                # validated at create; a rebuilt pool can disagree with
                # the budget bounds after manual scaling — run without
                # the policy rather than refuse to track the pool
                import logging

                logging.getLogger(__name__).warning(
                    "autoscaler for job %s disabled on rebuild: %s",
                    job_id, e)
            # replica template: prefer a SERVING worker's config — a
            # disaggregated job's worker 0 may be prefill-role, and a
            # scale-up cloning it would add capacity that never
            # answers queries (the autoscaler grows on serving
            # pressure). Fallback strips the role: a unified clone
            # serves either way.
            tmpl_cfg = next((dict(spec["config"])
                             for _i, _w, spec in workers
                             if spec["config"].get("role")
                             != "prefill"), None)
            if tmpl_cfg is None:
                tmpl_cfg = dict(workers[0][2]["config"])
                tmpl_cfg.pop("role", None)
            st = {"pool": [w for _, w, _ in workers],
                  "template": tmpl_cfg,
                  "module": workers[0][2]["module"],
                  "next_index": max(i for i, _, _ in workers) + 1,
                  "pool_version": 0.0, "policy": policy,
                  "warming": [], "victim": None,
                  "drain_timeout": 120.0}
            self._scaleout[job_id] = st
            return st

    def _publish_pool(self, job_id: str) -> None:
        """Write the job's routing-pool membership to the hub (the
        predictor's router applies the diff live). Version is a
        strictly increasing stamp so a late re-delivery can't roll the
        pool back."""
        with self.op_lock:
            st = self._scaleout.get(job_id)
            if st is None or not self.kv_port:
                return
            st["pool_version"] = max(time.time(),
                                     st["pool_version"] + 1e-4)
            members = {"workers": list(st["pool"]),
                       "version": st["pool_version"],
                       "published_at": time.time()}
        try:
            self._pool_hub().put_pool_members(job_id, members)
            self.scaling.inc("pool_publishes")
        except Exception:  # noqa: BLE001 — the hub may be mid-restart;
            # the next scale event (or tick) republishes
            import logging

            logging.getLogger(__name__).warning(
                "pool membership publish failed for job %s", job_id,
                exc_info=True)

    def _worker_sid(self, job_id: str, wid: str) -> Optional[str]:
        """service id of the job's worker ``wid`` (caller holds
        op_lock or tolerates a snapshot)."""
        for sid, spec in self._respawn_specs.items():
            if spec["service_type"] != ServiceType.INFERENCE_WORKER:
                continue
            if spec["meta_kwargs"].get("inference_job_id") != job_id:
                continue
            if spec["config"].get("worker_id") == wid:
                return sid
        return None

    def _scale_up_one(self, job_id: str,
                      slot_timeout: float) -> Optional[str]:
        """Spawn one extra replica from the job's template. The new
        worker starts WARMING: it joins the routing pool (and the
        published membership) only once its obs sidecar reports a port
        — a worker mid-compile must not attract streams. Returns the
        new worker id, or None when no device slot was free."""
        with self.op_lock:
            self._check_fence()
            if self._scaleout.get(job_id) is None:
                raise KeyError(f"no scale-out state for job {job_id!r}")
        # acquire the slot OUTSIDE op_lock: every release path (monitor
        # poll, stop_service, a draining victim's reap) needs that
        # lock, so blocking on the allocator while holding it could
        # never be satisfied by a concurrent release — the same
        # invariant create_inference_services documents
        slot = self.allocator.acquire(timeout=slot_timeout)
        if slot is None:
            return None
        with self.op_lock:
            st = self._scaleout.get(job_id)
            if st is None:  # job stopped between the locks
                self.allocator.release(slot)
                return None
            idx = st["next_index"]
            st["next_index"] += 1
            wid = f"iw-{job_id[:8]}-{idx}"
            cfg = dict(st["template"])
            cfg["worker_id"] = wid
            port_file = self.workdir / f"{wid}.obs_port"
            cfg["obs_port_file"] = str(port_file)
            try:
                port_file.unlink()  # a stale file from a previous life
            except OSError:         # must not instantly promote
                pass
            try:
                # scale-up holds op_lock across the spawn wait so the
                # claimed slot cannot be double-assigned (see
                # docs/linting.md "Admin op serialization")
                self._spawn(st["module"], cfg,  # rafiki: noqa[lock-order-cycle]
                            ServiceType.INFERENCE_WORKER, slot=slot,
                            inference_job_id=job_id)
            except Exception:
                self.allocator.release(slot)
                raise
            st["warming"].append({"wid": wid,
                                  "port_file": str(port_file),
                                  "since": time.monotonic()})
            self.scaling.inc("autoscale_ups")
            return wid

    def _promote_warmed(self, job_id: str,
                        st: Dict[str, Any]) -> None:
        """Move warmed-up replicas (obs port reported) into the routing
        pool and publish the new membership."""
        changed = False
        with self.op_lock:
            for item in list(st["warming"]):
                ready = Path(item["port_file"]).exists()
                timed_out = (time.monotonic() - item["since"]
                             > self.WARM_PUBLISH_TIMEOUT_S)
                if not ready and not timed_out:
                    continue
                st["warming"].remove(item)
                if item["wid"] not in st["pool"]:
                    st["pool"].append(item["wid"])
                changed = True
        if changed:
            self._publish_pool(job_id)

    def _begin_scale_down(self, job_id: str, wid: str) -> bool:
        """Start a drain-based scale-down of ``wid``: membership FIRST
        (the predictor stops routing there and fails over its streams
        with forced prefixes), then the graceful-drain request; the
        victim finishes in-flight work and exits 0 (reaped by the
        monitor). Crash-healing for the victim is de-registered so a
        non-zero exit while draining is not respawned."""
        with self.op_lock:
            st = self._scaleout.get(job_id)
            if st is None or st.get("victim"):
                return False
            if wid in st["pool"]:
                st["pool"].remove(wid)
            sid = self._worker_sid(job_id, wid)
            spec = self._respawn_specs.pop(sid, None) if sid else None
            cfg = dict((spec or {}).get("config") or {})
            if sid is not None and sid in self.services:
                st["victim"] = {"sid": sid, "wid": wid, "cfg": cfg,
                                "deadline": time.monotonic()
                                + st["drain_timeout"]}
        self._publish_pool(job_id)
        with self.op_lock:
            st = self._scaleout.get(job_id)
            victim = (st or {}).get("victim")
        if not victim:
            return False  # worker already gone: the pool just shrank
        self._request_drain(victim["cfg"])
        self.scaling.inc("autoscale_downs")
        return True

    def _victim_tick(self, job_id: str, st: Dict[str, Any]) -> None:
        """Advance an in-flight scale-down: a cleanly drained victim is
        reaped by the monitor poll (rc=0 → STOPPED, slot released); one
        that blows its drain deadline is terminated — a stuck scale-
        down must converge, not wedge the autoscaler forever."""
        with self.op_lock:
            v = st.get("victim")
            if not v:
                return
            if v["sid"] not in self.services:
                st["victim"] = None  # drained + reaped: done
                return
            overdue = time.monotonic() > v["deadline"]
        if overdue:
            import logging

            logging.getLogger(__name__).warning(
                "scale-down victim %s did not drain in time; "
                "terminating", v["wid"])
            self.stop_service(v["sid"])
            with self.op_lock:
                st["victim"] = None

    @staticmethod
    def _choose_victim(st: Dict[str, Any],
                       stats: Dict[str, Any]) -> Optional[str]:
        """Scale-down victim: the member with the fewest live KV pages
        (least in-flight state to fail over), ties to the most recently
        added — the pool shrinks newest-first by default.

        Prefill-role workers are never autoscale victims: the
        autoscaler manages SERVING capacity, and a prefill worker's
        near-zero page count would otherwise make it the first pick
        every time — silently destroying a tier the operator
        explicitly provisioned (scale-ups clone the serving
        template, so it would never come back)."""
        pool = []
        for w in st["pool"]:
            s = stats.get(w)
            if not (isinstance(s, dict) and s.get("role") == "prefill"):
                pool.append(w)
        if len(pool) <= 1:
            return None

        def pages(wid: str) -> float:
            s = stats.get(wid)
            if not isinstance(s, dict):
                return float("inf")
            v = s.get("engine_kv_pages_used", s.get("kv_pages_used"))
            return float(v) if isinstance(v, (int, float)) else \
                float("inf")

        return min(pool, key=lambda w: (pages(w), -pool.index(w)))

    def autoscale_tick(self, force: bool = False) -> List[Dict[str, Any]]:
        """One autoscaler evaluation (called from the admin monitor
        loop; self-rate-limited). Grows a job's pool on sustained
        admission stalls, shrinks it through the drain path when idle;
        promotes warmed replicas into the routing pool and converges
        stuck drains. Returns the actions taken (for tests/logs)."""
        actions: List[Dict[str, Any]] = []
        if self.fenced or not self.kv_port:
            return actions
        now = time.monotonic()
        if not force and now - self._last_autoscale_tick < \
                self.AUTOSCALE_TICK_EVERY_S:
            return actions
        self._last_autoscale_tick = now
        with self.op_lock:
            job_ids = set(self._scaleout)
            for spec in self._respawn_specs.values():
                if spec["service_type"] == ServiceType.INFERENCE_WORKER:
                    jid = spec["meta_kwargs"].get("inference_job_id")
                    if jid:
                        job_ids.add(jid)
        for job_id in sorted(job_ids):
            job = self.meta.get_inference_job(job_id)
            if job is None or job.get("status") != "RUNNING":
                with self.op_lock:
                    self._scaleout.pop(job_id, None)
                continue
            st = self._ensure_scaleout(job_id)
            if st is None:
                continue
            self._promote_warmed(job_id, st)
            self._victim_tick(job_id, st)
            with self.op_lock:
                policy = st.get("policy")
                busy = bool(st.get("victim") or st.get("warming")
                            or st.get("manual"))
                pool = list(st["pool"])
            if policy is None or busy:
                # no policy, or a previous action / an operator's
                # manual scale still converging — decisions wait until
                # the pool is quiescent (the policy must never fight
                # an in-flight operation)
                continue
            stats: Dict[str, Any] = {}
            for wid in pool:
                try:
                    stats[wid] = self._pool_hub().get_worker_stats(wid)
                except Exception:  # rafiki: noqa[silent-except] — a
                    stats[wid] = None  # hub hiccup reads as missing
            decision = policy.observe(stats)
            if decision == "up":
                try:
                    wid = self._scale_up_one(job_id, slot_timeout=0.0)
                except Exception as e:  # noqa: BLE001 — a failed spawn
                    # must not kill the monitor loop
                    import logging

                    logging.getLogger(__name__).warning(
                        "autoscale-up spawn for job %s failed: %s",
                        job_id, e)
                    wid = None
                if wid is None:
                    self.scaling.inc("autoscale_blocked")
                    actions.append({"job_id": job_id,
                                    "action": "blocked"})
                else:
                    actions.append({"job_id": job_id, "action": "up",
                                    "worker": wid})
            elif decision == "down":
                victim = self._choose_victim(st, stats)
                if victim and self._begin_scale_down(job_id, victim):
                    actions.append({"job_id": job_id, "action": "down",
                                    "worker": victim})
        return actions

    def scale_inference_job(self, job_id: str, workers: int,
                            drain_timeout: float = 120.0,
                            warm_timeout: float = 180.0
                            ) -> Dict[str, Any]:
        """Manual scale to an exact replica count (the operator's
        override; also stamps the autoscaler cooldown so the policy
        doesn't immediately fight the operator). Ups spawn from the
        job's template and block until the new workers report their
        obs port (joined the routing pool); downs drain newest-first,
        one at a time, and block until each victim exits."""
        self._check_fence()
        workers = int(workers)
        if workers < 1:
            raise ValueError(f"workers={workers} must be >= 1")
        st = self._ensure_scaleout(job_id)
        if st is None:
            raise KeyError(
                f"no live inference workers for job {job_id!r}")
        with self.op_lock:
            if st.get("manual"):
                raise RuntimeError(
                    f"a manual scale of job {job_id} is already in "
                    "progress — wait for it to finish")
            if len(self._pool_trial_ids(job_id, st)) > 1:
                raise RuntimeError(
                    f"job {job_id}'s replicas serve DISTINCT trials "
                    "(an ensemble) — scaling would clone one trial "
                    "and skew/evict the others; redeploy with "
                    "max_workers=1 (or MULTI_ADAPTER) to scale")
            # the busy flag + an up-front cooldown stamp keep the
            # autoscaler's tick out while this (possibly minutes-long,
            # drain-blocking) operation runs — the policy must not
            # undo the operator's target mid-flight
            st["manual"] = True
            policy = st.get("policy")
        if policy is not None:
            policy.note_action()
        try:
            return self._scale_to(job_id, st, workers, drain_timeout,
                                  warm_timeout)
        finally:
            with self.op_lock:
                st["manual"] = False
            if policy is not None:
                policy.note_action()  # cooldown runs from COMPLETION

    def _pool_trial_ids(self, job_id: str,
                        st: Dict[str, Any]) -> set:
        """Distinct ``trial_id`` values across the pool's worker
        configs (caller holds op_lock). More than one means the job is
        a cross-trial ensemble — cloning its template would double-
        weight one trial in the unary gather and a scale-down could
        evict another trial's only replica."""
        out = set()
        for wid in st["pool"]:
            sid = self._worker_sid(job_id, wid)
            spec = self._respawn_specs.get(sid) if sid else None
            out.add((spec or {}).get("config", {}).get("trial_id"))
        return out

    def _scale_to(self, job_id: str, st: Dict[str, Any], workers: int,
                  drain_timeout: float,
                  warm_timeout: float) -> Dict[str, Any]:
        result: Dict[str, Any] = {"job_id": job_id, "scaled_up": [],
                                  "scaled_down": []}
        with self.op_lock:
            current = len(st["pool"]) + len(st["warming"])
        while current < workers:
            wid = self._scale_up_one(job_id,
                                     slot_timeout=self.slot_timeout)
            if wid is None:
                raise RuntimeError(
                    f"no free device slot to scale job {job_id} to "
                    f"{workers} workers ({self.allocator.n_slots} "
                    f"slots, {self.allocator.free_count()} free)")
            result["scaled_up"].append(wid)
            current += 1
        deadline = time.monotonic() + warm_timeout
        while time.monotonic() < deadline:
            self._promote_warmed(job_id, st)
            with self.op_lock:
                if not st["warming"]:
                    break
            time.sleep(0.05)
        with self.op_lock:
            # blown warm deadline: publish anyway — the breakers gate a
            # worker that still is not serving
            for item in list(st["warming"]):
                st["warming"].remove(item)
                if item["wid"] not in st["pool"]:
                    st["pool"].append(item["wid"])
        self._publish_pool(job_id)
        while True:
            with self.op_lock:
                if len(st["pool"]) <= workers:
                    break
                victim = st["pool"][-1]
            self._scale_down_blocking(job_id, victim, drain_timeout)
            result["scaled_down"].append(victim)
        with self.op_lock:
            result["pool"] = list(st["pool"])
        return result

    def _scale_down_blocking(self, job_id: str, wid: str,
                             drain_timeout: float) -> None:
        """Manual-path scale-down: membership first, then drain, then
        wait for the exit (terminate on a blown deadline) — mirrors
        rolling_restart's reap-or-terminate contract."""
        with self.op_lock:
            st = self._scaleout.get(job_id)
            if st is None:
                return
            if wid in st["pool"]:
                st["pool"].remove(wid)
            sid = self._worker_sid(job_id, wid)
            spec = self._respawn_specs.pop(sid, None) if sid else None
            svc = self.services.get(sid) if sid else None
        self._publish_pool(job_id)
        if svc is None:
            return
        drain_sent = self._request_drain(
            dict((spec or {}).get("config") or {}))
        try:
            svc.proc.wait(timeout=drain_timeout if drain_sent
                          else min(5.0, drain_timeout))
        except subprocess.TimeoutExpired:
            import logging

            logging.getLogger(__name__).warning(
                "scale-down victim %s did not drain within %.0fs; "
                "terminating", wid, drain_timeout)
            svc.proc.terminate()
            try:
                svc.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                svc.proc.kill()
                svc.proc.wait()
        with self.op_lock:
            if sid in self.services:  # the monitor may have reaped the
                # clean rc=0 exit already
                self.meta.update_service(sid,
                                         status=ServiceStatus.STOPPED)
                if svc.slot is not None:
                    self.allocator.release(svc.slot)
                    svc.slot = None
                del self.services[sid]
        self.scaling.inc("autoscale_downs")

    def scaleout_status(self, job_id: str) -> Dict[str, Any]:
        """Pool + autoscaler state for the admin API/dashboard."""
        with self.op_lock:
            st = self._scaleout.get(job_id)
            if st is None:
                return {"enabled": False, "pool": [], "warming": [],
                        "victim": None}
            policy = st.get("policy")
            out = {"enabled": policy is not None,
                   "pool": list(st["pool"]),
                   "warming": [w["wid"] for w in st["warming"]],
                   "victim": (st.get("victim") or {}).get("wid"),
                   "drain_timeout_s": st["drain_timeout"]}
        if policy is not None:
            out.update(policy.status())
        return out

    def pending_respawn_job_ids(self) -> set:
        """Jobs that currently have a queued (slot-starved) worker
        respawn — they must count as busy, or the finalizers declare
        them done and the queued healing is dropped."""
        with self.op_lock:
            out = set()
            for item in self._pending_respawns:
                mk = item["spec"]["meta_kwargs"]
                jid = mk.get("train_job_id") or mk.get("inference_job_id")
                if jid:
                    out.add(jid)
            return out

    def stop_service(self, service_id: str, timeout: float = 10.0) -> None:
        self._check_fence()
        with self.op_lock:
            self._stop_service(service_id, timeout)

    def _stop_service(self, service_id: str, timeout: float) -> None:
        svc = self.services.get(service_id)
        if svc is None:
            return
        if svc.alive():
            svc.proc.terminate()
            try:
                svc.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                svc.proc.kill()
                svc.proc.wait()
        self.meta.update_service(service_id, status=ServiceStatus.STOPPED)
        if svc.slot is not None:
            self.allocator.release(svc.slot)
            svc.slot = None
        self._respawn_specs.pop(service_id, None)
        del self.services[service_id]

    def _drop_handles(self) -> None:
        """Fenced shutdown: the children (and their MetaStore rows) now
        belong to the admin that took the lease over — killing them
        would tear down the NEW admin's adopted stack. Release only our
        local bookkeeping."""
        for sid, svc in list(self.services.items()):
            if svc.slot is not None:
                try:
                    self.allocator.release(svc.slot)
                except ValueError:
                    pass
                svc.slot = None
            self._respawn_specs.pop(sid, None)
            del self.services[sid]
        self._kv_proc = None
        self.kv_host, self.kv_port = "", 0

    def stop_all(self) -> None:
        if self.fenced:
            self._drop_handles()
            return
        for sid in list(self.services):
            with self.op_lock:
                self._stop_service(sid, timeout=10.0)
        if self._kv_proc is not None and self._kv_server is not None:
            self._kv_server.stop()
            self._kv_proc = None
            self.kv_host, self.kv_port = "", 0
            if getattr(self, "_kv_service_id", None):
                self.meta.update_service(self._kv_service_id,
                                         status=ServiceStatus.STOPPED)
        self.release_lease()


class _DeadProc:
    """Popen-shaped placeholder for a kvd the reconciler found DEAD
    (row present, process gone): gives the respawn path a non-None,
    already-exited handle so data-plane supervision state stays
    uniform."""

    pid = 0
    returncode = -1

    def poll(self) -> int:
        return self.returncode


class _AdoptedKVServer:
    """KVServer-shaped handle over a rafiki-kvd the reconciler adopted
    (same ``host``/``port``/``_proc``/``stop()`` surface as
    :class:`rafiki_tpu.native.client.KVServer`)."""

    def __init__(self, host: str, port: int,
                 proc: AdoptedProcess) -> None:
        self.host, self.port = host, port
        self._proc = proc

    def stop(self) -> None:
        from ..native.client import KVClient

        try:
            KVClient(self.host, self.port).shutdown()
        except OSError:
            pass
        try:
            self._proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self._proc.kill()
