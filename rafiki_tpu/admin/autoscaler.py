"""Inference-pool autoscaling policy: grow on stalls, shrink when idle.

The decision core of the control-plane autoscaler
(``ServicesManager.autoscale_tick``), factored out so the policy is
unit-testable without processes. It consumes the same per-worker stats
the workers already publish to the hub (PR 5/6 gauges) and emits at
most one decision per observation:

- **"up"** after ``grow_stall_ticks`` *consecutive* observations in
  which the pool's cumulative ``admission_stalls`` counter grew —
  admissions queuing behind a full KV page pool is the one signal that
  directly means "a whole extra engine's worth of demand exists"
  (a high page ratio alone is healthy utilization).
- **"down"** after ``shrink_idle_ticks`` consecutive observations with
  zero stall growth AND every worker's page-pool ratio under
  ``shrink_pages_ratio`` — the pool is provably over-provisioned and a
  drained worker's load fits in its siblings' headroom.
- **None** otherwise — including whenever any pool member's stats are
  missing (a respawning/unobservable worker blocks *shrink* decisions:
  scaling down a pool you can't see is how streams get dropped) and
  during the post-action ``cooldown_s`` (the previous action's effect
  must be visible in the signals before the next one).

Scale-down safety is the caller's contract: the victim leaves the
routing pool first, then drains through the existing graceful-drain
path — a shrink never drops a stream.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping, Optional


def _num(stats: Mapping[str, Any], name: str) -> Optional[float]:
    """A numeric signal accepting both the hub-publish spelling
    (``engine_admission_stalls``) and the bare engine spelling."""
    for key in (f"engine_{name}", name):
        v = stats.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None


@dataclass
class AutoscaleConfig:
    """Operator-facing bounds, parsed from the inference-job budget.

    Budget keys: ``AUTOSCALE`` (truthy enables the monitor-tick
    policy), ``MAX_WORKERS`` (required — the pool's upper bound),
    ``MIN_WORKERS`` (lower bound, default 1), and
    ``AUTOSCALE_COOLDOWN_S`` (floor between scale actions, default
    30)."""

    min_workers: int = 1
    max_workers: int = 1
    cooldown_s: float = 30.0
    #: consecutive stalling observations before growing
    grow_stall_ticks: int = 2
    #: consecutive idle observations before shrinking
    shrink_idle_ticks: int = 5
    #: every worker's page ratio must sit under this to shrink
    shrink_pages_ratio: float = 0.5

    @classmethod
    def from_budget(cls, budget: Mapping[str, Any],
                    initial_workers: int) -> Optional["AutoscaleConfig"]:
        """Parse + validate the budget's autoscale keys at the API
        surface (a bad bound fails the create call, not a monitor tick
        hours later). None when ``AUTOSCALE`` is unset; the dependent
        keys without it raise — a silently ignored bound is worse than
        an error."""
        budget = budget or {}
        dependent = [k for k in ("MIN_WORKERS", "MAX_WORKERS",
                                 "AUTOSCALE_COOLDOWN_S") if k in budget]
        if not budget.get("AUTOSCALE"):
            if dependent:
                raise ValueError(
                    f"budget key(s) {dependent} require AUTOSCALE in "
                    "the same budget (they bound the autoscaler)")
            return None
        if "MAX_WORKERS" not in budget:
            # defaulting the ceiling to the initial count would make
            # the headline grow-on-stalls behavior a silent no-op —
            # the bound the operator armed AUTOSCALE for must be named
            raise ValueError(
                "AUTOSCALE requires MAX_WORKERS in the same budget "
                "(the pool's upper bound; without one the policy "
                "could never scale up)")
        mn = int(budget.get("MIN_WORKERS", 1))
        mx = int(budget["MAX_WORKERS"])
        cd = float(budget.get("AUTOSCALE_COOLDOWN_S", 30.0))
        if mn < 1:
            raise ValueError(f"MIN_WORKERS={mn} must be >= 1 (an empty "
                             "pool serves nothing)")
        if mx < mn:
            raise ValueError(
                f"MAX_WORKERS={mx} must be >= MIN_WORKERS={mn}")
        if not (mn <= initial_workers <= mx):
            raise ValueError(
                f"initial replica count {initial_workers} must lie in "
                f"[MIN_WORKERS={mn}, MAX_WORKERS={mx}] — the autoscaler "
                "bounds must contain the starting pool")
        if cd <= 0:
            raise ValueError(
                f"AUTOSCALE_COOLDOWN_S={cd} must be > 0 (back-to-back "
                "scale actions oscillate)")
        return cls(min_workers=mn, max_workers=mx, cooldown_s=cd)


class AutoscalePolicy:
    """Per-job scaling state machine over published worker stats."""

    def __init__(self, cfg: AutoscaleConfig,
                 now: Callable[[], float] = time.monotonic) -> None:
        self.cfg = cfg
        self._now = now
        #: last seen cumulative admission_stalls per worker id
        self._stalls: Dict[str, float] = {}
        self._stall_ticks = 0
        self._idle_ticks = 0
        self._last_action_at = 0.0
        self._last_set: frozenset = frozenset()
        self.last_decision = ""

    def note_action(self) -> None:
        """Stamp an externally performed scale action (manual scale,
        the caller executing a decision) so the cooldown applies to it
        too."""
        self._last_action_at = self._now()
        self._stall_ticks = 0
        self._idle_ticks = 0

    def status(self) -> Dict[str, Any]:
        return {"min_workers": self.cfg.min_workers,
                "max_workers": self.cfg.max_workers,
                "cooldown_s": self.cfg.cooldown_s,
                "stall_ticks": self._stall_ticks,
                "idle_ticks": self._idle_ticks,
                "last_decision": self.last_decision,
                "cooldown_remaining_s": round(max(
                    0.0, self._last_action_at + self.cfg.cooldown_s
                    - self._now()), 3)}

    def observe(self, stats_by_worker: Mapping[str, Optional[Mapping]]
                ) -> Optional[str]:
        """Fold one round of per-worker stats; return "up", "down", or
        None. Callers execute the decision (and the cooldown stamps
        itself here)."""
        n = len(stats_by_worker)
        wids = frozenset(stats_by_worker)
        if wids != self._last_set:
            # the pool changed under us (scale action, manual scale,
            # respawn rename): accrued tick evidence described another
            # pool — start fresh rather than e.g. instantly shrinking
            # a just-grown pool on stale idle ticks
            self._last_set = wids
            self._stall_ticks = 0
            self._idle_ticks = 0
        stall_delta = 0.0
        pages_ok = True
        missing = False
        for wid, s in stats_by_worker.items():
            if not isinstance(s, Mapping):
                missing = True
                continue
            stalls = _num(s, "admission_stalls")
            if stalls is not None:
                prev = self._stalls.get(wid)
                if prev is not None and stalls > prev:
                    stall_delta += stalls - prev
                self._stalls[wid] = stalls
            used = _num(s, "kv_pages_used")
            total = _num(s, "kv_pages_total")
            if used is not None and total:
                if used / total >= self.cfg.shrink_pages_ratio:
                    pages_ok = False
        # drop watermark entries for departed workers so a scale-down
        # followed by a same-id scale-up can't read a stale baseline
        for wid in list(self._stalls):
            if wid not in stats_by_worker:
                del self._stalls[wid]

        if stall_delta > 0:
            self._stall_ticks += 1
            self._idle_ticks = 0
        else:
            self._stall_ticks = 0
            if not missing and pages_ok:
                self._idle_ticks += 1
            else:
                self._idle_ticks = 0

        now = self._now()
        in_cooldown = now - self._last_action_at < self.cfg.cooldown_s \
            and self._last_action_at > 0
        if in_cooldown:
            return None
        if self._stall_ticks >= self.cfg.grow_stall_ticks \
                and n < self.cfg.max_workers:
            self.note_action()
            self.last_decision = "up"
            return "up"
        if self._idle_ticks >= self.cfg.shrink_idle_ticks \
                and n > self.cfg.min_workers:
            self.note_action()
            self.last_decision = "down"
            return "down"
        return None
