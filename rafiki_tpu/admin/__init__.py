"""Control plane: Admin brain, REST app, service orchestration."""

from .admin import Admin, AuthError
from .services_manager import ManagedService, ServicesManager

__all__ = ["Admin", "AuthError", "ServicesManager", "ManagedService"]
