"""`rafiki-tpu stack` — start/stop/status of the full local service stack.

Parity target: the reference's ``scripts/start.sh`` / ``stop.sh``
(SURVEY.md §2 "Deployment"): one command brings up the whole topology.
Here that is a single detached Admin process (which itself owns the
data-plane server and spawns advisors/workers/predictors); state lives
under ``--workdir``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

from ..utils.http import json_request


def stack_command(args: argparse.Namespace) -> int:
    workdir = Path(args.workdir).absolute()
    pid_file = workdir / "admin.pid"
    url_file = workdir / "admin.url"

    if args.action == "start":
        if pid_file.exists() and _pid_alive(int(pid_file.read_text())):
            print(f"stack already running (pid {pid_file.read_text()})",
                  file=sys.stderr)
            return 1
        workdir.mkdir(parents=True, exist_ok=True)
        cfg = {"workdir": str(workdir), "db_path": str(workdir / "meta.db"),
               "host": "127.0.0.1", "port": args.port,
               "slot_size": getattr(args, "slot_size", 1),
               "port_file": str(workdir / "admin.port")}
        cfg_path = workdir / "admin.json"
        cfg_path.write_text(json.dumps(cfg))
        log = open(workdir / "admin.log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "rafiki_tpu.admin.app",
             "--config", str(cfg_path)],
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        log.close()
        port_file = workdir / "admin.port"
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if port_file.exists() and port_file.read_text().strip():
                break
            if proc.poll() is not None:
                print(f"admin died on startup; see {workdir / 'admin.log'}",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)
        else:
            proc.kill()
            print("admin did not come up in time", file=sys.stderr)
            return 1
        port = int(port_file.read_text().strip())
        url = f"http://127.0.0.1:{port}"
        pid_file.write_text(str(proc.pid))
        url_file.write_text(url)
        print(f"stack up: {url} (pid {proc.pid})")
        print("login: superadmin@rafiki / rafiki")
        return 0

    if args.action == "stop":
        if not pid_file.exists():
            print("stack is not running", file=sys.stderr)
            return 1
        pid = int(pid_file.read_text())
        if _pid_alive(pid):
            os.kill(pid, signal.SIGTERM)
            for _ in range(100):
                if not _pid_alive(pid):
                    break
                time.sleep(0.1)
            else:
                os.kill(pid, signal.SIGKILL)
        pid_file.unlink(missing_ok=True)
        print("stack stopped")
        return 0

    if args.action == "status":
        if not url_file.exists():
            print("stack is not running")
            return 1
        url = url_file.read_text().strip()
        try:
            health = json_request("GET", f"{url}/health", timeout=5)
        except OSError:
            print(f"stack at {url} is not answering")
            return 1
        print(json.dumps({"url": url, **health}))
        return 0

    print(f"unknown stack action {args.action!r}", file=sys.stderr)
    return 2


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False
