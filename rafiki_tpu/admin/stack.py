"""`rafiki-tpu stack` — start/stop/status of the full local service stack.

Parity target: the reference's ``scripts/start.sh`` / ``stop.sh``
(SURVEY.md §2 "Deployment"): one command brings up the whole topology.
Here that is a single detached Admin process (which itself owns the
data-plane server and spawns advisors/workers/predictors); state lives
under ``--workdir``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

from ..utils.http import json_request


def stack_command(args: argparse.Namespace) -> int:
    workdir = Path(args.workdir).absolute()
    pid_file = workdir / "admin.pid"
    url_file = workdir / "admin.url"

    if args.action == "start":
        if pid_file.exists() and _pid_alive(int(pid_file.read_text())):
            print(f"stack already running (pid {pid_file.read_text()})",
                  file=sys.stderr)
            return 1
        workdir.mkdir(parents=True, exist_ok=True)
        cfg = {"workdir": str(workdir), "db_path": str(workdir / "meta.db"),
               "host": "127.0.0.1", "port": args.port,
               "slot_size": args.slot_size, "workers": args.workers,
               "cold_start": bool(getattr(args, "cold", False)),
               "port_file": str(workdir / "admin.port")}
        cfg_path = workdir / "admin.json"
        cfg_path.write_text(json.dumps(cfg))
        # a stale port file from a previous (killed) admin would make
        # the wait loop below declare the stack up before the new admin
        # has even bound — e.g. while it is still waiting out a dead
        # predecessor's lease TTL
        (workdir / "admin.port").unlink(missing_ok=True)
        log = open(workdir / "admin.log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "rafiki_tpu.admin.app",
             "--config", str(cfg_path)],
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        log.close()
        port_file = workdir / "admin.port"
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if port_file.exists() and port_file.read_text().strip():
                break
            if proc.poll() is not None:
                # a lease-fenced boot exits rc=3 with a structured JSON
                # error on its last log line — surface it verbatim
                if proc.returncode == 3:
                    try:
                        last = (workdir / "admin.log").read_bytes() \
                            .decode(errors="replace").strip() \
                            .splitlines()[-1]
                        err = json.loads(last)
                        print(f"admin refused to start: "
                              f"{err.get('detail', last)}",
                              file=sys.stderr)
                        return 3
                    except (OSError, ValueError, IndexError):
                        pass
                print(f"admin died on startup; see {workdir / 'admin.log'}",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)
        else:
            proc.kill()
            print("admin did not come up in time", file=sys.stderr)
            return 1
        port = int(port_file.read_text().strip())
        url = f"http://127.0.0.1:{port}"
        pid_file.write_text(str(proc.pid))
        url_file.write_text(url)
        print(f"stack up: {url} (pid {proc.pid})")
        print("login: superadmin@rafiki / rafiki")
        return 0

    if args.action == "stop":
        if not pid_file.exists():
            print("stack is not running", file=sys.stderr)
            return 1
        pid = int(pid_file.read_text())
        if _pid_alive(pid):
            os.kill(pid, signal.SIGTERM)
            for _ in range(100):
                if not _pid_alive(pid):
                    break
                time.sleep(0.1)
            else:
                os.kill(pid, signal.SIGKILL)
        pid_file.unlink(missing_ok=True)
        orphans = _reap_orphans(workdir)
        if orphans:
            print(f"killed {orphans} orphaned service processes",
                  file=sys.stderr)
        print("stack stopped")
        return 0

    if args.action == "status":
        if not url_file.exists():
            print("stack is not running")
            return 1
        url = url_file.read_text().strip()
        try:
            health = json_request("GET", f"{url}/health", timeout=5)
        except OSError:
            print(f"stack at {url} is not answering")
            return 1
        print(json.dumps({"url": url, **health}))
        return 0

    print(f"unknown stack action {args.action!r}", file=sys.stderr)
    return 2


def _pid_alive(pid: int) -> bool:
    """Zombie-aware: a SIGKILLed admin whose parent has not reaped it
    yet still answers signal 0, but it is dead for every purpose here —
    `stack start` must not refuse to restart over a corpse."""
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    from .proc import proc_state

    return proc_state(pid) != "Z"


def _reap_orphans(workdir: Path) -> int:
    """Kill service processes that outlived the admin (e.g. the admin was
    SIGKILLed so its graceful shutdown never ran) and mark their MetaStore
    rows STOPPED. The admin records every child's pid — and its kernel
    start time — in the services table, so the stack CLI can finish the
    cleanup from the db alone. Kills are identity-gated on
    (cmdline, start_time): a recycled pid can never be killed, even by
    another rafiki process that happens to reuse the number."""
    db = workdir / "meta.db"
    if not db.exists():
        return 0
    from ..store.meta_store import MetaStore
    from .proc import identity_matches, terminate_pid

    meta = MetaStore(str(db))
    killed = 0
    for row in meta.get_services():
        if row["status"] in ("STOPPED", "ERRORED", "CRASHED"):
            continue
        pid = int(row.get("pid") or 0)
        start_time = float(row.get("start_time") or 0)
        if pid > 0 and identity_matches(pid, start_time):
            if terminate_pid(pid, start_time):
                killed += 1
        meta.update_service(row["id"], status="STOPPED")
    return killed
