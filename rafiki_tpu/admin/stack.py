"""`rafiki-tpu stack` — start/stop/status of the full local service stack.

Parity target: the reference's ``scripts/start.sh`` / ``stop.sh``
(SURVEY.md §2 "Deployment"): one command brings up the whole topology.
Here that is a single detached Admin process (which itself owns the
data-plane server and spawns advisors/workers/predictors); state lives
under ``--workdir``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

from ..utils.http import json_request


def stack_command(args: argparse.Namespace) -> int:
    workdir = Path(args.workdir).absolute()
    pid_file = workdir / "admin.pid"
    url_file = workdir / "admin.url"

    if args.action == "start":
        if pid_file.exists() and _pid_alive(int(pid_file.read_text())):
            print(f"stack already running (pid {pid_file.read_text()})",
                  file=sys.stderr)
            return 1
        workdir.mkdir(parents=True, exist_ok=True)
        cfg = {"workdir": str(workdir), "db_path": str(workdir / "meta.db"),
               "host": "127.0.0.1", "port": args.port,
               "slot_size": args.slot_size, "workers": args.workers,
               "port_file": str(workdir / "admin.port")}
        cfg_path = workdir / "admin.json"
        cfg_path.write_text(json.dumps(cfg))
        log = open(workdir / "admin.log", "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "rafiki_tpu.admin.app",
             "--config", str(cfg_path)],
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        log.close()
        port_file = workdir / "admin.port"
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if port_file.exists() and port_file.read_text().strip():
                break
            if proc.poll() is not None:
                print(f"admin died on startup; see {workdir / 'admin.log'}",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)
        else:
            proc.kill()
            print("admin did not come up in time", file=sys.stderr)
            return 1
        port = int(port_file.read_text().strip())
        url = f"http://127.0.0.1:{port}"
        pid_file.write_text(str(proc.pid))
        url_file.write_text(url)
        print(f"stack up: {url} (pid {proc.pid})")
        print("login: superadmin@rafiki / rafiki")
        return 0

    if args.action == "stop":
        if not pid_file.exists():
            print("stack is not running", file=sys.stderr)
            return 1
        pid = int(pid_file.read_text())
        if _pid_alive(pid):
            os.kill(pid, signal.SIGTERM)
            for _ in range(100):
                if not _pid_alive(pid):
                    break
                time.sleep(0.1)
            else:
                os.kill(pid, signal.SIGKILL)
        pid_file.unlink(missing_ok=True)
        orphans = _reap_orphans(workdir)
        if orphans:
            print(f"killed {orphans} orphaned service processes",
                  file=sys.stderr)
        print("stack stopped")
        return 0

    if args.action == "status":
        if not url_file.exists():
            print("stack is not running")
            return 1
        url = url_file.read_text().strip()
        try:
            health = json_request("GET", f"{url}/health", timeout=5)
        except OSError:
            print(f"stack at {url} is not answering")
            return 1
        print(json.dumps({"url": url, **health}))
        return 0

    print(f"unknown stack action {args.action!r}", file=sys.stderr)
    return 2


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def _reap_orphans(workdir: Path) -> int:
    """Kill service processes that outlived the admin (e.g. the admin was
    SIGKILLed so its graceful shutdown never ran) and mark their MetaStore
    rows STOPPED. The admin records every child's pid in the services
    table, so the stack CLI can finish the cleanup from the db alone."""
    db = workdir / "meta.db"
    if not db.exists():
        return 0
    from ..store.meta_store import MetaStore

    meta = MetaStore(str(db))
    killed = 0
    for row in meta.get_services():
        if row["status"] in ("STOPPED", "ERRORED"):
            continue
        pid = int(row.get("pid") or 0)
        if pid > 0 and _pid_alive(pid) and _looks_like_service(pid):
            try:
                os.kill(pid, signal.SIGTERM)
                for _ in range(50):
                    if not _pid_alive(pid):
                        break
                    time.sleep(0.1)
                else:
                    os.kill(pid, signal.SIGKILL)
                killed += 1
            except (ProcessLookupError, PermissionError):
                pass  # exited between the check and the kill
        meta.update_service(row["id"], status="STOPPED")
    return killed


def _looks_like_service(pid: int) -> bool:
    """Guard against recycled pids: only kill processes whose cmdline
    looks like one of ours (rafiki service module or the kv daemon)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return False
    return "rafiki" in cmd
