"""Admin: the control-plane brain behind the REST API.

Parity target: the reference's ``Admin`` class (SURVEY.md §2 "Admin",
§3.1/§3.2): auth, model upload, dataset registry, train/inference-job
lifecycle; spawns services through the ServicesManager. Auth tokens are
random in-process session tokens (the reference uses JWT-style bearer
tokens against the same Flask process).

A monitor thread replaces the reference's implicit Docker restart/status
machinery: it reaps dead services and finalizes train jobs whose workers
have all exited (stopping their advisors), i.e. the failure-detection loop
of SURVEY.md §5.3.
"""

from __future__ import annotations

import secrets
import threading
import time
from typing import Any, Dict, List, Optional

from ..constants import (ServiceType, SubTrainJobStatus, TrainJobStatus,
                         UserType)
from ..store.meta_store import MetaStore
from .services_manager import ServicesManager


class AuthError(Exception):
    pass


class Admin:
    def __init__(self, meta_store: MetaStore,
                 services_manager: ServicesManager,
                 superadmin_email: str = "superadmin@rafiki",
                 superadmin_password: str = "rafiki") -> None:
        self.meta = meta_store
        self.services = services_manager
        self._tokens: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        if self.meta.get_user_by_email(superadmin_email) is None:
            self.meta.create_user(superadmin_email, superadmin_password,
                                  UserType.SUPERADMIN)

    # ---- lifecycle ----
    def start_monitor(self, interval_s: float = 0.5) -> None:
        # lease renewal lives on the ServicesManager's OWN heartbeat
        # thread (started at acquire_lease time, before reconcile —
        # and idempotent here): it never takes op_lock, so a spawn's
        # 180s port-wait cannot starve the heartbeat past the TTL and
        # hand the stack to a concurrent boot
        self.services.start_lease_heartbeat()

        def loop() -> None:
            while not self._monitor_stop.wait(interval_s):
                try:
                    # a fenced admin must stop respawning/finalizing:
                    # the children now belong to the new admin
                    if self.services.fenced:
                        continue
                    self.services.poll()
                    # inference-pool autoscaler: grow on sustained
                    # admission stalls, shrink through the drain path
                    # (self-rate-limited; no-op without armed jobs)
                    self.services.autoscale_tick()
                    self._finalize_finished_train_jobs()
                except Exception:  # keep the monitor alive — but a
                    # broken poll loop must be visible, not silent
                    import logging

                    logging.getLogger(__name__).warning(
                        "service monitor tick failed", exc_info=True)

        self._monitor = threading.Thread(target=loop, daemon=True)
        self._monitor.start()

    def stop(self) -> None:
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        self.services.stop_all()  # also stops the lease heartbeat

    def _finalize_finished_train_jobs(self) -> None:
        running = [s for s in self.services.services.values()
                   if s.service_type == ServiceType.TRAIN_WORKER]
        busy_jobs = set()
        for s in running:
            row = self.meta.get_service(s.service_id)
            if row and row.get("train_job_id"):
                busy_jobs.add(row["train_job_id"])
        # a queued (slot-starved) worker respawn keeps its job busy —
        # finalizing here would drop the healing on the floor
        busy_jobs |= self.services.pending_respawn_job_ids()
        for svc in list(self.services.services.values()):
            if svc.service_type != ServiceType.ADVISOR:
                continue
            row = self.meta.get_service(svc.service_id)
            job_id = row.get("train_job_id") if row else None
            if job_id and job_id not in busy_jobs:
                self.services.stop_service(svc.service_id)
                for sub in self.meta.get_sub_train_jobs_of_train_job(job_id):
                    self.meta.update_sub_train_job(
                        sub["id"], status=SubTrainJobStatus.STOPPED)
                self.meta.update_train_job(job_id,
                                           status=TrainJobStatus.STOPPED,
                                           stopped_at=time.time())
                # natural completion is the COMMON finalization path —
                # it must sweep leaked mid-train ckpts too, not just
                # explicit stop_train_job
                self._sweep_trial_checkpoints(job_id)

    # ---- auth ----
    def login(self, email: str, password: str) -> Dict[str, Any]:
        user = self.meta.authenticate_user(email, password)
        if user is None:
            raise AuthError("invalid email or password")
        token = secrets.token_hex(16)
        with self._lock:
            self._tokens[token] = user["id"]
        return {"token": token, "user_id": user["id"],
                "user_type": user["user_type"]}

    def authorize(self, token: str) -> Dict[str, Any]:
        with self._lock:
            user_id = self._tokens.get(token)
        user = self.meta.get_user(user_id) if user_id else None
        if user is None or user.get("banned"):
            raise AuthError("invalid or expired token")
        return user

    def create_user(self, email: str, password: str,
                    user_type: str) -> Dict[str, Any]:
        u = self.meta.create_user(email, password, user_type)
        return {k: u[k] for k in ("id", "email", "user_type")}

    # ---- control-plane backup ----
    def backup(self, path: str) -> Dict[str, Any]:
        """Online MetaStore snapshot (consistent under concurrent
        writers) — the pre-risky-ops step of the recovery runbook."""
        return self.meta.backup(path)

    # ---- models ----
    def create_model(self, user_id: str, name: str, task: str,
                     model_class: str, model_bytes: bytes,
                     access_right: str = "PRIVATE") -> Dict[str, Any]:
        from ..model.base import load_model_class

        load_model_class(model_bytes, model_class)  # validate importable
        m = self.meta.create_model(user_id, name, task, model_class,
                                   model_bytes, access_right=access_right)
        return _model_public(m)

    def get_models(self, user_id: str,
                   task: Optional[str] = None) -> List[Dict[str, Any]]:
        return [_model_public(m)
                for m in self.meta.get_available_models(task=task,
                                                        user_id=user_id)]

    # ---- datasets ----
    def create_dataset(self, user_id: str, name: str, task: str,
                       uri: str) -> Dict[str, Any]:
        return self.meta.create_dataset(user_id, name, task, uri)

    def get_datasets(self, user_id: str,
                     task: Optional[str] = None) -> List[Dict[str, Any]]:
        return self.meta.get_datasets(user_id, task=task)

    # ---- train jobs ----
    def create_train_job(self, user_id: str, app: str, task: str,
                         train_dataset_id: str, val_dataset_id: str,
                         budget: Dict[str, Any],
                         model_ids: Optional[List[str]] = None,
                         train_args: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        latest = self.meta.get_latest_train_job_of_app(user_id, app)
        version = (latest["app_version"] + 1) if latest else 1
        # datasets may be registered ids or raw host paths; reject
        # anything that is neither HERE, at the API boundary — otherwise
        # a typo'd dataset id only fails later inside a worker
        import os

        for ds_id in (train_dataset_id, val_dataset_id):
            if self.meta.get_dataset(ds_id) is None and \
                    not os.path.exists(ds_id):
                raise ValueError(
                    f"dataset {ds_id!r} is neither a registered dataset "
                    "id nor an existing path")
        train_uri = self._resolve_dataset(train_dataset_id)
        val_uri = self._resolve_dataset(val_dataset_id)

        if model_ids is None:
            models = self.meta.get_available_models(task=task,
                                                    user_id=user_id)
            model_ids = [m["id"] for m in models]
        if not model_ids:
            raise ValueError(f"no models available for task {task!r}")

        job = self.meta.create_train_job(
            user_id, app, version, task, budget,
            train_uri, val_uri, train_args=train_args)
        for mid in model_ids:
            self.meta.create_sub_train_job(job["id"], mid)
        try:
            self.services.create_train_services(job["id"])
        except ValueError:
            # pre-spawn validation failed (e.g. typo'd knob_overrides):
            # don't leave a zombie RUNNING job (or STARTED sub-jobs — the
            # monitor's finalize path never runs for a job with no
            # services) behind the 400 response
            for sub in self.meta.get_sub_train_jobs_of_train_job(job["id"]):
                self.meta.update_sub_train_job(sub["id"], status="ERRORED")
            self.meta.update_train_job(job["id"], status="ERRORED")
            raise
        return self.get_train_job(job["id"])

    def _resolve_dataset(self, dataset_id_or_uri: str) -> str:
        ds = self.meta.get_dataset(dataset_id_or_uri)
        return ds["uri"] if ds is not None else dataset_id_or_uri

    def get_train_job(self, job_id: str) -> Dict[str, Any]:
        job = self.meta.get_train_job(job_id)
        if job is None:
            raise KeyError(f"no train job {job_id!r}")
        job["sub_train_jobs"] = \
            self.meta.get_sub_train_jobs_of_train_job(job_id)
        job["n_trials"] = len(self.meta.get_trials_of_train_job(job_id))
        return job

    def get_train_jobs(self, user_id: str) -> List[Dict[str, Any]]:
        """All of a user's train jobs, newest first (dashboard listing)."""
        return self.meta.get_train_jobs_of_user(user_id)

    def get_train_job_of_app(self, user_id: str, app: str,
                             app_version: int = -1) -> Dict[str, Any]:
        if app_version < 0:
            job = self.meta.get_latest_train_job_of_app(user_id, app)
        else:
            jobs = self.meta.get_train_jobs_of_app(user_id, app)
            job = next((j for j in jobs
                        if j["app_version"] == app_version), None)
        if job is None:
            raise KeyError(f"no train job for app {app!r}")
        return self.get_train_job(job["id"])

    def stop_train_job(self, job_id: str) -> None:
        # mark STOPPED FIRST: the monitor's respawner checks job status,
        # so a worker that crashes in this very window is not replaced
        # behind our back (the service snapshot below would miss it)
        self.meta.update_train_job(job_id, status=TrainJobStatus.STOPPED,
                                   stopped_at=time.time())
        for svc in list(self.services.services.values()):
            row = self.meta.get_service(svc.service_id)
            if row and row.get("train_job_id") == job_id:
                self.services.stop_service(svc.service_id)
        for sub in self.meta.get_sub_train_jobs_of_train_job(job_id):
            self.meta.update_sub_train_job(sub["id"],
                                           status=SubTrainJobStatus.STOPPED)
        self._sweep_trial_checkpoints(job_id)

    def _sweep_trial_checkpoints(self, job_id: str) -> None:
        """Drop ``ckpt-<trial_id>`` working blobs once the job is done.
        Mid-train checkpoints of preempted trials that were never resumed
        (respawn budget exhausted, job stopped) and of failed resumes
        otherwise live forever in the ParamStore (ADVICE r3); after job
        finalization nothing will ever resume them. ALL trials are swept
        — including RUNNING zombies whose worker was SIGKILLed (the
        state a preemption leaves behind): the job's worker pool is gone,
        so no claimant remains. Final trial params (key = trial_id) are
        artifacts and are kept — deployment reads them."""
        from ..store.param_store import ParamStore

        try:
            store = ParamStore.from_uri(self.services.param_store_uri)
            for t in self.meta.get_trials_of_train_job(job_id):
                store.delete(f"ckpt-{t['id']}")
                store.delete(f"ckpt-{t['id']}-meta")
        except Exception:  # noqa: BLE001 — a kv hiccup must not turn a
            # clean job stop into a 500; the leak is bounded and logged
            import logging

            logging.getLogger(__name__).warning(
                "trial checkpoint sweep failed for job %s", job_id,
                exc_info=True)

    def get_trials(self, job_id: str) -> List[Dict[str, Any]]:
        return self.meta.get_trials_of_train_job(job_id)

    def get_best_trials(self, job_id: str,
                        max_count: int = 2) -> List[Dict[str, Any]]:
        return self.meta.get_best_trials_of_train_job(job_id,
                                                      max_count=max_count)

    def get_trial_logs(self, trial_id: str) -> List[Dict[str, Any]]:
        return self.meta.get_trial_logs(trial_id)

    # ---- inference jobs ----
    def create_inference_job(self, user_id: str, train_job_id: str,
                             max_workers: int = 2,
                             budget: Optional[Dict[str, Any]] = None
                             ) -> Dict[str, Any]:
        """``budget`` options: ``STEPS_PER_SYNC`` (decode-loop dispatch
        amortization), ``MULTI_ADAPTER`` (serve the best-N LM trials as
        one stacked-adapter worker instead of N replicas),
        ``ADAPTIVE_GATHER`` (latency/accuracy gather controller),
        ``MAX_NEW_TOKENS`` / ``SYSTEM_PREFIX`` (decode-loop generation
        cap / shared-prefix KV cache), ``SPECULATE_K`` (speculative
        decoding: prompt-lookup drafting at depth K),
        ``DRAFT_TRIAL_ID`` (a completed same-template trial to use as
        the draft MODEL instead of prompt lookup), and the autoscaler
        keys ``AUTOSCALE`` / ``MIN_WORKERS`` / ``MAX_WORKERS`` /
        ``AUTOSCALE_COOLDOWN_S`` (grow the pool on sustained admission
        stalls, shrink through the drain path — see
        docs/operations.md "Scale-out & autoscaling")."""
        job = self.meta.create_inference_job(user_id, train_job_id,
                                             budget=budget)
        self.services.create_inference_services(job["id"],
                                                max_workers=max_workers)
        return self.get_inference_job(job["id"])

    def get_inference_job(self, job_id: str) -> Dict[str, Any]:
        job = self.meta.get_inference_job(job_id)
        if job is None:
            raise KeyError(f"no inference job {job_id!r}")
        host = job.get("predictor_host") or ""
        job["predictor_url"] = f"http://{host}" if host else None
        return job

    def get_inference_jobs(self, user_id: str) -> List[Dict[str, Any]]:
        jobs = self.meta.get_inference_jobs(user_id)
        for job in jobs:
            host = job.get("predictor_host") or ""
            job["predictor_url"] = f"http://{host}" if host else None
        return jobs

    def get_inference_job_health(self, job_id: str) -> Dict[str, Any]:
        """Server-side proxy to the predictor's ``GET /health`` (req/s
        counters + latency percentiles): the dashboard cannot fetch the
        predictor's port directly from the browser (cross-origin)."""
        from ..utils.http import json_request

        job = self.get_inference_job(job_id)
        if not job.get("predictor_url"):
            return {"ok": False, "error": "no predictor"}
        try:
            return json_request("GET", f"{job['predictor_url']}/health",
                                timeout=5)
        except Exception as e:  # noqa: BLE001 — unreachable/500/garbage
            # predictor all map to a structured "down" answer, never a
            # 500 from the admin itself
            return {"ok": False, "error": str(e)}

    def rolling_restart_inference_job(self, job_id: str,
                                      drain_timeout: float = 120.0
                                      ) -> Dict[str, Any]:
        """Cycle the job's workers with zero dropped streams: each is
        drained (finishes in-flight work while the predictor routes
        around it), stopped, and respawned before the next one goes."""
        job = self.meta.get_inference_job(job_id)
        if job is None:
            raise KeyError(f"no inference job {job_id!r}")
        if job["status"] != "RUNNING":
            raise ValueError(
                f"inference job {job_id} is {job['status']}, not "
                "RUNNING — nothing to restart")
        return self.services.rolling_restart(job_id,
                                             drain_timeout=drain_timeout)

    def scale_inference_job(self, job_id: str, workers: int,
                            drain_timeout: float = 120.0
                            ) -> Dict[str, Any]:
        """Manually scale a RUNNING inference job's worker pool to an
        exact replica count: ups spawn from the job's template and join
        the routing pool once warmed; downs drain newest-first (the
        predictor fails their streams over with forced prefixes — a
        shrink never drops a stream)."""
        job = self.meta.get_inference_job(job_id)
        if job is None:
            raise KeyError(f"no inference job {job_id!r}")
        if job["status"] != "RUNNING":
            raise ValueError(
                f"inference job {job_id} is {job['status']}, not "
                "RUNNING — nothing to scale")
        return self.services.scale_inference_job(
            job_id, workers, drain_timeout=drain_timeout)

    def get_inference_job_autoscaler(self, job_id: str
                                     ) -> Dict[str, Any]:
        """The job's routing pool + autoscaler state (bounds, tick
        counters, in-flight warmups/drains)."""
        if self.meta.get_inference_job(job_id) is None:
            raise KeyError(f"no inference job {job_id!r}")
        return self.services.scaleout_status(job_id)

    def stop_inference_job(self, job_id: str) -> None:
        # STOPPED first — same respawn-race reasoning as stop_train_job
        self.meta.update_inference_job(job_id, status="STOPPED",
                                       stopped_at=time.time())
        for svc in list(self.services.services.values()):
            row = self.meta.get_service(svc.service_id)
            if row and row.get("inference_job_id") == job_id:
                self.services.stop_service(svc.service_id)


def _model_public(m: Dict[str, Any]) -> Dict[str, Any]:
    return {k: m[k] for k in
            ("id", "name", "task", "model_class", "access_right",
             "created_at")}
