"""Admin REST API over the JSON HTTP kit.

Parity target: the reference's Flask route table (SURVEY.md §2 "Admin",
§3.1): tokens, users, models, datasets, train jobs, trials, inference
jobs. Model bytes travel base64-encoded in JSON (the reference posts
pickled classes as multipart; source-code-as-bytes is the transport here —
see ``model.base.serialize_model_class``).
"""

from __future__ import annotations

import base64
from typing import Any, Dict, Optional, Tuple

from ..obs import (PROM_CONTENT_TYPE, MetricsRegistry, TraceBuffer,
                   mint_trace_id)
from ..utils.http import JsonHttpService, RawResponse
from .admin import Admin, AuthError


class AdminApp:
    def __init__(self, admin: Admin, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.admin = admin
        # control-plane metrics: live gauges evaluated at scrape time
        # against the ServicesManager (no second bookkeeping), plus the
        # HTTP request counter/latency the service kit wires itself
        self.metrics = MetricsRegistry()
        self.traces = TraceBuffer(256)
        svcs = admin.services
        self.metrics.gauge("admin_services",
                           "live managed service processes",
                           fn=lambda: len(svcs.services))
        self.metrics.gauge("admin_free_slots",
                           "unallocated device sub-mesh slots",
                           fn=lambda: svcs.allocator.free_count())
        self.metrics.gauge(
            "admin_respawns_done", "self-healing worker respawns",
            fn=lambda: svcs.respawn_stats()["respawns_done"])
        self.metrics.gauge(
            "admin_pending_respawns", "slot-starved respawns queued",
            fn=lambda: svcs.respawn_stats()["pending_respawns"])
        # crash-recovery plane: what the boot reconciler did and where
        # the single-writer lease stands (docs/observability.md)
        self.metrics.gauge(
            "admin_services_adopted",
            "live services re-adopted by the boot reconciler",
            fn=lambda: svcs.recovery["services_adopted"])
        self.metrics.gauge(
            "admin_orphans_reaped",
            "stopped-job survivors killed by the boot reconciler",
            fn=lambda: svcs.recovery["orphans_reaped"])
        self.metrics.gauge(
            "admin_services_crashed",
            "service rows found dead at boot (CRASHED)",
            fn=lambda: svcs.recovery["services_crashed"])
        self.metrics.gauge(
            "admin_lease_takeovers",
            "expired-lease takeovers performed by this admin",
            fn=lambda: svcs.recovery["lease_takeovers"])
        self.metrics.gauge(
            "admin_lease_generation",
            "fencing generation of the held admin lease",
            fn=lambda: svcs.lease_generation)
        # scale-out plane: autoscaler actions (docs/observability.md)
        self.metrics.gauge(
            "admin_autoscale_ups",
            "inference-pool replicas added by autoscale/manual scale",
            fn=lambda: svcs.scaling["autoscale_ups"])
        self.metrics.gauge(
            "admin_autoscale_downs",
            "inference-pool replicas drained out by autoscale/manual "
            "scale", fn=lambda: svcs.scaling["autoscale_downs"])
        self.metrics.gauge(
            "admin_autoscale_blocked",
            "autoscale-up decisions skipped for want of a device slot",
            fn=lambda: svcs.scaling["autoscale_blocked"])
        # data-plane persistence health, re-exported from the kvd's
        # STATS verb (kvd_up / kvd_wal_bytes / kvd_snapshot_age_s /
        # kvd_last_fsync_age_s / kvd_replay_seconds / kvd_respawns —
        # docs/observability.md). Cached inside kvd_metrics so a
        # scrape costs at most one socket round-trip per 2s.
        self.metrics.register_stats(svcs.kvd_metrics)
        self.http = JsonHttpService(host, port, registry=self.metrics)
        r = self.http.route
        # /metrics is numeric-only and stays open like /health; the
        # trace ring carries job ids/app names — USER-owned metadata —
        # so unlike the (by-design unauthenticated) worker/predictor
        # surfaces, the admin's /debug/requests sits behind auth
        r("GET", "/metrics", self._metrics)
        r("GET", "/debug/requests", self._auth(self._debug_requests))
        r("POST", "/tokens", self._login)
        r("GET", "/health", self._health)
        r("GET", "/", self._dashboard)
        r("GET", "/train_jobs", self._auth(self._get_train_jobs))
        r("POST", "/users", self._auth(self._create_user))
        r("POST", "/models", self._auth(self._create_model))
        r("GET", "/models", self._auth(self._get_models))
        r("POST", "/datasets", self._auth(self._create_dataset))
        r("GET", "/datasets", self._auth(self._get_datasets))
        r("POST", "/train_jobs", self._auth(self._create_train_job))
        r("GET", "/train_jobs/app/<app>", self._auth(self._get_job_of_app))
        r("GET", "/train_jobs/<id>", self._auth(self._get_train_job))
        r("POST", "/train_jobs/<id>/stop", self._auth(self._stop_train_job))
        r("GET", "/train_jobs/<id>/trials", self._auth(self._get_trials))
        r("GET", "/train_jobs/<id>/best_trials",
          self._auth(self._get_best_trials))
        r("GET", "/trials/<id>/logs", self._auth(self._get_trial_logs))
        r("POST", "/inference_jobs", self._auth(self._create_inference_job))
        r("GET", "/inference_jobs", self._auth(self._get_inference_jobs))
        r("GET", "/inference_jobs/<id>", self._auth(self._get_inference_job))
        r("GET", "/inference_jobs/<id>/health",
          self._auth(self._get_inference_job_health))
        r("POST", "/inference_jobs/<id>/stop",
          self._auth(self._stop_inference_job))
        r("POST", "/inference_jobs/<id>/rolling_restart",
          self._auth(self._rolling_restart))
        r("POST", "/inference_jobs/<id>/scale",
          self._auth(self._scale_inference_job))
        r("GET", "/inference_jobs/<id>/autoscaler",
          self._auth(self._get_autoscaler))
        r("POST", "/system/backup", self._auth(self._backup))

    def start(self) -> Tuple[str, int]:
        return self.http.start()

    def stop(self) -> None:
        self.http.stop()
        self.admin.stop()

    # ---- middleware ----
    def _auth(self, handler):
        def wrapped(m: Dict[str, str], body: Any,
                    headers: Dict[str, str]) -> Tuple[int, Any]:
            hdrs = {k.lower(): v for k, v in headers.items()}
            token = (hdrs.get("authorization") or "").removeprefix(
                "Bearer ").strip()
            try:
                user = self.admin.authorize(token)
            except AuthError as e:
                return 401, {"error": str(e)}
            try:
                return handler(m, body or {}, user)
            except (KeyError, ValueError) as e:
                return 400, {"error": str(e)}

        return wrapped

    # ---- routes ----
    def _metrics(self, _m, _b, _h) -> Tuple[int, Any]:
        return 200, RawResponse(
            self.metrics.render_prometheus().encode("utf-8"),
            PROM_CONTENT_TYPE)

    def _debug_requests(self, m, _b, _user) -> Tuple[int, Any]:
        from ..obs import DEBUG_REQUESTS_DEFAULT_N

        n = int(m.get("n", DEBUG_REQUESTS_DEFAULT_N))  # a bad n is a
        # ValueError -> the _auth wrapper's 400, same as other routes
        if n < 0:
            return 400, {"error": "n must be >= 0"}
        recs = self.traces.recent(n)
        return 200, {"requests": recs, "count": len(recs)}

    def _dashboard(self, _m, _b, _h) -> Tuple[int, Any]:
        """Operator dashboard (SURVEY.md §1 layer 1): a self-contained
        HTML+JS page over this very REST API — jobs → trials → loss
        curves from ``/trials/<id>/logs``."""
        import importlib.resources

        try:
            html = (importlib.resources.files("rafiki_tpu.admin")
                    / "dashboard.html").read_bytes()
        except (FileNotFoundError, ModuleNotFoundError):
            return 404, {"error": "dashboard.html not packaged"}
        return 200, RawResponse(html, "text/html; charset=utf-8")

    def _get_train_jobs(self, _m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_train_jobs(user["id"])

    def _health(self, _m, _b, _h) -> Tuple[int, Any]:
        svc = self.admin.services
        # respawn_stats/degraded_jobs are lock-protected: the monitor
        # thread mutates the underlying dicts while this thread reads
        # jobs whose self-healing is exhausted/lost (job id → reason):
        # a job quietly running under-replicated must be visible here,
        # not just in a warning log. Fetched FIRST — degraded_jobs()
        # prunes STOPPED jobs, and the count must describe the same
        # pruned view the map shows (a monitor alerting on the counter
        # must find its job in the list)
        degraded = svc.degraded_jobs()
        return 200, {"ok": True,
                     "n_services": len(svc.services),
                     "free_slots": svc.allocator.free_count(),
                     **svc.respawn_stats(),
                     "degraded_jobs": len(degraded),
                     "degraded": degraded,
                     # autoscaler action counters (per-job detail lives
                     # at GET /inference_jobs/<id>/autoscaler)
                     "scaling": svc.scaling.snapshot(),
                     # boot-reconciler outcome + lease state: feeds the
                     # dashboard's recovery banner
                     "recovery": svc.recovery_stats(),
                     # kvd persistence + supervision (feeds the
                     # dashboard's data-plane banner)
                     "data_plane": svc.data_plane_status()}

    def _login(self, _m, body, _h) -> Tuple[int, Any]:
        try:
            return 200, self.admin.login(body["email"], body["password"])
        except AuthError as e:
            return 401, {"error": str(e)}

    def _create_user(self, _m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.create_user(body["email"], body["password"],
                                           body.get("user_type",
                                                    "APP_DEVELOPER"))

    def _create_model(self, _m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.create_model(
            user["id"], body["name"], body["task"], body["model_class"],
            base64.b64decode(body["model_bytes"]),
            access_right=body.get("access_right", "PRIVATE"))

    def _get_models(self, _m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.get_models(user["id"],
                                          task=body.get("task"))

    def _create_dataset(self, _m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.create_dataset(user["id"], body["name"],
                                              body["task"], body["uri"])

    def _get_datasets(self, _m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.get_datasets(user["id"],
                                            task=body.get("task"))

    def _create_train_job(self, _m, body, user) -> Tuple[int, Any]:
        job = self.admin.create_train_job(
            user["id"], body["app"], body["task"],
            body["train_dataset_id"], body["val_dataset_id"],
            body.get("budget", {"TRIAL_COUNT": 5}),
            model_ids=body.get("model_ids"),
            train_args=body.get("train_args"))
        # job lifecycle lands in the admin's own /debug/requests ring
        self.traces.start(mint_trace_id(), request_id=str(job["id"]),
                          span="create_train_job", app=body["app"])
        return 200, job

    def _get_train_job(self, m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_train_job(m["id"])

    def _get_job_of_app(self, m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.get_train_job_of_app(
            user["id"], m["app"], int(body.get("app_version", -1)))

    def _stop_train_job(self, m, _b, user) -> Tuple[int, Any]:
        self.admin.stop_train_job(m["id"])
        return 200, {"ok": True}

    def _get_trials(self, m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_trials(m["id"])

    def _get_best_trials(self, m, body, user) -> Tuple[int, Any]:
        return 200, self.admin.get_best_trials(
            m["id"], max_count=int(body.get("max_count", 2)))

    def _get_trial_logs(self, m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_trial_logs(m["id"])

    def _create_inference_job(self, _m, body, user) -> Tuple[int, Any]:
        try:
            budget = body.get("budget")
            job = self.admin.create_inference_job(
                user["id"], body["train_job_id"],
                max_workers=int(body.get("max_workers", 2)),
                budget=budget if isinstance(budget, dict) else None)
        except RuntimeError as e:
            return 409, {"error": str(e)}
        self.traces.start(mint_trace_id(), request_id=str(job["id"]),
                          span="create_inference_job")
        return 200, job

    def _get_inference_job(self, m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_inference_job(m["id"])

    def _get_inference_jobs(self, _m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_inference_jobs(user["id"])

    def _get_inference_job_health(self, m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_inference_job_health(m["id"])

    def _stop_inference_job(self, m, _b, user) -> Tuple[int, Any]:
        self.admin.stop_inference_job(m["id"])
        return 200, {"ok": True}

    def _backup(self, _m, body, user) -> Tuple[int, Any]:
        """Online MetaStore snapshot to a server-side path — the
        "before risky ops" half of the recovery runbook. Superadmin
        only: the path lands on the admin host's filesystem."""
        from ..constants import UserType

        if user.get("user_type") not in (UserType.SUPERADMIN,
                                         UserType.ADMIN):
            return 403, {"error": "backup requires an admin user"}
        path = str(body.get("path") or "")
        if not path:
            return 400, {"error": "body must name a backup 'path'"}
        try:
            return 200, {"ok": True, **self.admin.backup(path)}
        except NotImplementedError as e:
            return 501, {"error": str(e)}
        except OSError as e:
            return 500, {"error": f"backup failed: {e}"}

    def _scale_inference_job(self, m, body, user) -> Tuple[int, Any]:
        """Manual pool scaling: ``{"workers": N}`` grows from the
        job's template / drains newest-first down to N with zero
        dropped streams."""
        if "workers" not in (body or {}):
            return 400, {"error": "body must name 'workers' (the "
                                  "target replica count)"}
        try:
            return 200, self.admin.scale_inference_job(
                m["id"], int(body["workers"]),
                drain_timeout=float(body.get("drain_timeout", 120.0)))
        except RuntimeError as e:
            # no free slot / conflicting operation: a conflict with
            # current capacity, not a server bug
            return 409, {"error": str(e)}

    def _get_autoscaler(self, m, _b, user) -> Tuple[int, Any]:
        return 200, self.admin.get_inference_job_autoscaler(m["id"])

    def _rolling_restart(self, m, body, user) -> Tuple[int, Any]:
        """Zero-downtime worker cycling: drain→stop→respawn each of the
        job's workers one at a time (deploys/config reloads that must
        not drop a stream)."""
        try:
            return 200, self.admin.rolling_restart_inference_job(
                m["id"], drain_timeout=float(
                    (body or {}).get("drain_timeout", 120.0)))
        except RuntimeError as e:
            # already-in-progress / no free slot: a conflict with the
            # current state, not a server bug — 409 like the other
            # resource-conflict paths
            return 409, {"error": str(e)}


def main(argv: Optional[list] = None) -> int:
    """Service entrypoint: ``python -m rafiki_tpu.admin.app``."""
    import argparse
    import json

    from ..utils.platform import apply_platform_env

    apply_platform_env()

    from ..store.meta_store import MetaStore
    from .services_manager import LeaseHeldError, ServicesManager

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True,
                        help="JSON: {workdir, db_path, host, port, "
                             "slot_size, port_file, lease_ttl_s}")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)

    meta = MetaStore(cfg["db_path"])
    manager = ServicesManager(meta, cfg["workdir"],
                              slot_size=int(cfg.get("slot_size", 1)),
                              default_workers=int(cfg.get("workers", 1)))
    # single-writer fencing: refuse to run against a MetaStore a LIVE
    # admin owns (a duplicate boot would spawn a second stack on chips
    # the first still holds); an EXPIRED lease is taken over with a
    # bumped fencing generation. A crash-restart lands here within the
    # dead holder's TTL, so retry for lease_wait_s (default TTL + 5 s)
    # before giving up — a LIVE holder keeps renewing and wins every
    # retry, so duplicates are still refused (lease_wait_s=0 restores
    # strict fail-fast).
    import time as _time

    ttl_s = float(cfg.get("lease_ttl_s", 15.0))
    wait_s = float(cfg.get("lease_wait_s", ttl_s + 5.0))
    lease_deadline = _time.monotonic() + wait_s
    while True:
        try:
            lease = manager.acquire_lease(ttl_s=ttl_s)
            break
        except LeaseHeldError as e:
            if _time.monotonic() < lease_deadline:
                _time.sleep(0.25)
                continue
            # structured error on stdout (→ admin.log) so `stack start`
            # and operators see WHY the boot was refused
            print(json.dumps({"error": "admin_lease_held",
                              "detail": str(e), "lease": e.lease}),
                  flush=True)
            return 3
    if lease.get("took_over"):
        print(f"took over expired admin lease (generation "
              f"{lease['generation']})", flush=True)
    # heartbeat BEFORE reconcile: reconciling can exceed the TTL
    # (per-orphan kill grace, health probes) and an unrenewed lease
    # would let a concurrent boot take over mid-reconcile
    manager.start_lease_heartbeat()
    if cfg.get("cold_start"):
        # operator opt-out of adoption (`stack start --cold`): kill
        # every recorded survivor and boot from a clean slate — for
        # when the previous stack's state is not to be trusted
        reaped = manager.reap_stale_services()
        print(f"cold start: reaped {reaped} stale service row(s)",
              flush=True)
    else:
        # crash-only boot: re-adopt surviving services, crash+respawn
        # the dead, reap orphans — the rows are the source of truth
        recovery = manager.reconcile()
        print("reconciled: "
              f"{recovery['services_adopted']} adopted, "
              f"{recovery['services_crashed']} crashed, "
              f"{recovery['orphans_reaped']} orphans reaped",
              flush=True)
    manager.start_data_plane()

    # deterministic chaos: arm the admin-suicide timer and/or the
    # data-plane kill timer when configured (RAFIKI_CHAOS
    # kill_admin_after_s / kill_kvd_after_s — the "SIGKILL mid-load"
    # drills). The kvd killer takes a CALLABLE pid so it targets
    # whatever kvd is live when it fires (the supervisor may have
    # respawned it since arming).
    from ..chaos import ChaosConfig, arm_admin_kill, arm_kvd_kill

    chaos_cfg = ChaosConfig.from_env()
    if chaos_cfg is not None:
        arm_admin_kill(chaos_cfg)
        arm_kvd_kill(chaos_cfg,
                     lambda: (manager._kv_proc.pid
                              if manager._kv_proc is not None else 0))
    admin = Admin(meta, manager)
    admin.start_monitor()
    app = AdminApp(admin, cfg.get("host", "127.0.0.1"),
                   int(cfg.get("port", 0)))
    host, port = app.start()
    if cfg.get("port_file"):
        with open(cfg["port_file"], "w") as f:
            f.write(str(port))
    print(f"admin on {host}:{port}", flush=True)

    # graceful shutdown: SIGTERM/SIGINT unblock serve_forever so the
    # finally clause stops the monitor, every child service, and the kv
    # data plane — `stack stop`'s SIGTERM must not orphan workers
    import signal

    def _on_term(_signum, _frame):
        app.http.stop()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    try:
        app.http.serve_forever()
    finally:
        app.stop()
        print("admin stopped cleanly", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
