"""Workdir drift audit: MetaStore rows vs live pids vs slots vs ports.

``rafiki-tpu doctor --workdir W`` compares the four places control-plane
state lives — the MetaStore's ``services`` rows, the actual process
table (``/proc``, identity-checked via recorded kernel start times),
the recorded sub-mesh device assignments, and the ``*.obs_port``
sidecar files — and prints every disagreement as a drift finding. Zero
drift = the recorded world matches the real one; anything else is what
an operator (or the recovery tests) needs to see before trusting a
restarted control plane.

Pure read-only: the audit never signals, spawns, or writes — it is safe
to run against a LIVE stack.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from .proc import identity_matches, pid_alive

#: row states that claim a live process
_LIVE_STATES = ("STARTED", "RUNNING")


def audit_workdir(workdir: str,
                  db_path: Optional[str] = None) -> Dict[str, Any]:
    """Audit ``workdir`` and return the drift report (JSON-ready).

    ``report["drift"]`` is the flat list of human-readable findings;
    ``report["ok"]`` is True iff it is empty. Per-service detail rows
    live under ``report["services"]``.
    """
    wd = Path(workdir)
    db = Path(db_path) if db_path else wd / "meta.db"
    report: Dict[str, Any] = {
        "workdir": str(wd), "db_path": str(db), "checked_at": time.time(),
        "services": [], "drift": [], "ok": True}
    drift: List[str] = report["drift"]
    if not db.exists():
        drift.append(f"no MetaStore at {db} — nothing to audit against")
        report["ok"] = False
        return report

    from ..store.meta_store import MetaStore

    # mode=ro connection: the audit must be INCAPABLE of writing (or
    # schema-migrating) a live stack's database, not merely polite
    meta = MetaStore(str(db), read_only=True)
    rows = meta.get_services()
    claimed_ports: set = set()
    device_owners: Dict[int, str] = {}
    for row in rows:
        pid = int(row.get("pid") or 0)
        start_time = float(row.get("start_time") or 0)
        spec = row.get("spawn_spec") or {}
        status = row["status"]
        alive = pid_alive(pid) if pid > 0 else False
        ident = identity_matches(pid, start_time) if alive else False
        entry = {
            "id": row["id"], "service_type": row["service_type"],
            "status": status, "pid": pid, "pid_alive": alive,
            "identity_ok": ident, "start_time": start_time,
            "port": int(row.get("port") or 0),
            "devices": _devices(row), "has_spawn_spec": bool(spec)}
        label = f"{row['service_type']} {row['id'][:8]}"
        if status in _LIVE_STATES:
            if not ident:
                drift.append(
                    f"{label}: row is {status} but pid {pid} is "
                    + ("a DIFFERENT process (identity mismatch — "
                       "recycled pid?)" if alive else "dead"))
            else:
                # live and ours: check its recorded probe channel and
                # claim its devices for the double-booking check
                port = _probe_port(row, spec, wd)
                entry["probe_port"] = port
                if port:
                    claimed_ports.add(port)
                    entry["probe_ok"] = _http_alive(
                        row.get("host") or "127.0.0.1", port)
                    if not entry["probe_ok"]:
                        drift.append(
                            f"{label}: pid {pid} is alive but port "
                            f"{port} does not answer")
                for dev in entry["devices"]:
                    if dev in device_owners:
                        drift.append(
                            f"{label}: device {dev} is also recorded "
                            f"for {device_owners[dev]} (double-booked "
                            "sub-mesh)")
                    device_owners[dev] = label
        else:  # terminal row
            if ident:
                drift.append(
                    f"{label}: row is {status} but pid {pid} is still "
                    "alive (orphaned process)")
            if status in ("ERRORED", "CRASHED") and not spec and \
                    row["service_type"] in ("TRAIN_WORKER",
                                            "INFERENCE_WORKER"):
                drift.append(
                    f"{label}: crashed worker row has no spawn_spec — "
                    "unrecoverable by the reconciler (pre-recovery row?)")
        report["services"].append(entry)

    # obs_port sidecar files with no live owner are stale turds that can
    # mislead the next drain/adoption
    stale_ports = []
    for pf in sorted(wd.glob("*.obs_port")):
        try:
            port = int(pf.read_text().strip())
        except (OSError, ValueError):
            drift.append(f"{pf.name}: unreadable obs_port file")
            continue
        if port not in claimed_ports and not _http_alive("127.0.0.1",
                                                         port):
            stale_ports.append(pf.name)
    if stale_ports:
        drift.append(
            f"stale obs_port files (no live service on the recorded "
            f"port): {', '.join(stale_ports)}")

    report["data_plane"] = _audit_data_plane(rows, drift)

    lease = meta.get_admin_lease()
    if lease:
        age = time.time() - float(lease.get("heartbeat_at") or 0)
        report["lease"] = {**lease, "heartbeat_age_s": round(age, 1)}
        live_rows = any(s["status"] in _LIVE_STATES
                        for s in report["services"])
        if live_rows and age > 60.0:  # rafiki: noqa[taint-wall-clock-flow] — heartbeat_at is a PERSISTED wall-clock stamp from another process; monotonic cannot age it across restarts
            drift.append(
                f"admin lease heartbeat is {age:.0f}s old while "
                "service rows claim to be live — the admin is gone; "
                "restart it (it will re-adopt survivors)")
    report["n_services"] = len(rows)
    report["ok"] = not drift
    return report


def render_text(report: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`audit_workdir`'s report."""
    lines = [f"workdir audit: {report['workdir']}"]
    for s in report.get("services", []):
        mark = "ok " if (s["status"] not in _LIVE_STATES
                         or s["identity_ok"]) else "DRIFT"
        lines.append(
            f"[{mark}] {s['service_type']:<17} {s['id'][:8]} "
            f"{s['status']:<8} pid={s['pid']} "
            f"alive={str(s['pid_alive']).lower()} "
            f"identity={str(s['identity_ok']).lower()}"
            + (f" devices={s['devices']}" if s["devices"] else ""))
    dp = report.get("data_plane")
    if dp:
        rep = dp.get("replay") or {}
        lines.append(
            f"data plane: kvd {dp.get('host')}:{dp.get('port')} "
            f"reachable={str(dp.get('reachable')).lower()} "
            f"wal_bytes={dp.get('wal_bytes')} "
            f"last_fsync_age={dp.get('last_fsync_age_s')}s "
            f"replay_ok={str(rep.get('ok')).lower()} "
            f"replayable_records={rep.get('replayable_records')}")
    lease = report.get("lease")
    if lease:
        lines.append(
            f"lease: holder={str(lease.get('holder', ''))[:12]} "
            f"generation={lease.get('generation')} "
            f"heartbeat_age={lease.get('heartbeat_age_s')}s")
    if report["drift"]:
        lines.append(f"DRIFT ({len(report['drift'])} finding(s)):")
        lines.extend(f"  - {d}" for d in report["drift"])
    else:
        lines.append("no drift: recorded state matches the live world")
    return "\n".join(lines)


def _audit_data_plane(rows: List[Dict[str, Any]],
                      drift: List[str]) -> Optional[Dict[str, Any]]:
    """The kvd data-plane check: reachable on its recorded port,
    WAL/snapshot present under the recorded ``--data-dir``, last-fsync
    age (from the STATS verb), and a DRY-RUN replay integrity verdict
    over the persistence files (read-only; corruption a real boot
    would refuse is drift). Returns the report block, or None when no
    data-plane row exists."""
    live = [r for r in rows
            if r["service_type"] == "DATA_PLANE"
            and r["status"] in _LIVE_STATES]
    if not live:
        dead = [r for r in rows if r["service_type"] == "DATA_PLANE"]
        row = dead[-1] if dead else None
    else:
        row = live[-1]
    if row is None:
        return None
    spec_cfg = (row.get("spawn_spec") or {}).get("config") or {}
    host = row.get("host") or "127.0.0.1"
    port = int(row.get("port") or 0)
    data_dir = str(spec_cfg.get("data_dir") or "")
    block: Dict[str, Any] = {
        "row_id": row["id"], "status": row["status"],
        "host": host, "port": port, "data_dir": data_dir,
        "reachable": False}
    label = f"DATA_PLANE {row['id'][:8]}"
    if port > 0:
        try:
            from ..native.client import KVClient

            c = KVClient(host, port, connect_timeout=2.0)
            try:
                block["reachable"] = c.ping()
                stats = c.stats()
            finally:
                c.close()
            block["last_fsync_age_s"] = stats.get("last_fsync_age_s")
            block["wal_bytes"] = stats.get("wal_bytes")
            block["snapshot_age_s"] = stats.get("snapshot_age_s")
            block["fsync_policy"] = stats.get("fsync_policy")
            if not stats.get("persist_enabled"):
                drift.append(
                    f"{label}: kvd is serving WITHOUT persistence "
                    "(no --data-dir) — a crash loses every blob and "
                    "queue")
            else:
                age = stats.get("last_fsync_age_s")
                if isinstance(age, (int, float)) and age > 30.0:
                    drift.append(
                        f"{label}: last WAL fsync was {age:.0f}s ago "
                        "under a non-`no` policy — the fsync loop "
                        "looks wedged (host-crash durability window "
                        "is growing)")
        except (OSError, RuntimeError) as e:
            block["probe_error"] = str(e)
            if row["status"] in _LIVE_STATES:
                drift.append(
                    f"{label}: row is {row['status']} but the kvd at "
                    f"{host}:{port} does not answer ({e}) — restart "
                    "the admin (it respawns the kvd with WAL replay)")
    if data_dir:
        from ..native import wal as kvwal

        replay = kvwal.dry_run_replay(data_dir)
        block["replay"] = replay
        if not replay["ok"]:
            for f in replay["findings"]:
                drift.append(f"{label}: {f}")
    elif row["status"] in _LIVE_STATES:
        drift.append(
            f"{label}: no data_dir recorded in the spawn spec — the "
            "supervisor cannot respawn-with-replay (pre-persistence "
            "row?)")
    return block


def _devices(row: Dict[str, Any]) -> List[int]:
    try:
        return [int(d) for d in json.loads(row.get("devices") or "[]")]
    except (ValueError, TypeError):
        return []


def _probe_port(row: Dict[str, Any], spec: Dict[str, Any],
                wd: Path) -> int:
    port = int(row.get("port") or 0)
    if port > 0:
        return port
    port_file = ((spec.get("config") or {}).get("obs_port_file")
                 if spec else None)
    if port_file and Path(port_file).exists():
        try:
            return int(Path(port_file).read_text().strip())
        except (OSError, ValueError):
            return 0
    return 0


def _http_alive(host: str, port: int) -> bool:
    """TCP-level liveness: any process accepting on the port counts
    (not every service has /health; the audit checks reachability,
    not route tables)."""
    import socket

    try:
        with socket.create_connection((host, port), timeout=2.0):
            return True
    except OSError:
        return False


__all__ = ["audit_workdir", "render_text"]
