"""Gang-compiled tuning: vmap K hyperparameter configs into one
compiled train step (Podracer/Anakin pattern — see ``gang.py``)."""

from .gang import GangEngine, supports_gang

__all__ = ["GangEngine", "supports_gang"]
