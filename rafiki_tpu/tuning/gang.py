"""Gang-compiled tuning engine: K trials as K lanes of ONE compiled step.

The advisor stack schedules one process per trial, which is right for
Llama-sized templates but wasteful for the small zoo (MLP / tabular /
CNN-lite), where XLA compile + per-step dispatch dominate the trial wall
clock. This engine adopts the Anakin pattern from "Podracer
architectures for scalable RL" (PAPERS.md, arXiv:2104.06272): ``vmap``
K hyperparameter configurations of the same template into one
jit-compiled train step on one mesh, so the interpreter cost is paid
once per *gang*, not once per trial.

Mechanics:

- A **lane** is a trial. Per-lane *traceable* knobs (learning rate,
  dropout, ...) ride as traced ``[K]`` operands; all other knobs are
  burned into the compiled program, so proposals are grouped into
  **static buckets** by :func:`rafiki_tpu.model.knob.static_signature`
  — one compile per bucket, never per trial.
- The advisor issues batched suggestions (``propose_batch``); ASHA/BOHB
  rung exits are evaluated at epoch boundaries and **cull lanes in
  place**: a finished lane is refilled from the advisor's next batch
  with no recompile (a promotion refill warm-starts from the parent
  trial's in-engine param snapshot, optimizer fresh — exactly the
  sequential warm-start semantics).
- Each lane consumes the SAME batch schedule the template's sequential
  ``train()`` would (per-lane epoch counters seed the batch iterator),
  so a lane's training is bit-for-bit the sequential trial's training —
  tier-1 asserts score equivalence and that culling decisions match
  process mode.

``mode="sequential"`` runs the identical schedule through the
template's ordinary per-trial ``train()``/``evaluate()`` path (what a
process-per-trial deployment does) — the equivalence baseline and the
fallback for templates without a ``make_gang_spec``.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple, Type

import numpy as np

from ..advisor.base import Proposal, TrialResult
from ..model.base import BaseModel, TrainContext
from ..model.knob import (Knobs, static_signature, traceable_knobs,
                          validate_override_keys)
from ..model.log import ModelLogger


def supports_gang(model_class: Type[BaseModel]) -> bool:
    """True when the template implements the gang contract
    (``make_gang_spec`` + ``gang_epochs``)."""
    return (callable(getattr(model_class, "make_gang_spec", None))
            and callable(getattr(model_class, "gang_epochs", None)))


class _VmapExec:
    """One static bucket's compiled executor: stacked lane state, per-lane
    hp arrays, and the jitted vmapped train/eval functions (built once,
    reused across gang sessions of the same bucket)."""

    def __init__(self, spec, gang_size: int) -> None:
        import jax
        import jax.numpy as jnp

        self.spec = spec
        self.k = gang_size
        self._jnp = jnp
        # lanes vmap over state and hp; the batch is per-lane too (axis
        # 0) so each lane sees the batch schedule its sequential twin
        # would (lane epochs differ after an in-place refill)
        if gang_size == 1:
            # a 1-lane gang must BE the sequential trial bit-for-bit
            # (the tier-1 equivalence contract is exact equality, and
            # ANY graph change around the spec's functions — a vmap
            # lane axis, even squeeze/expand reshapes traced into the
            # same jit — can perturb XLA fusion in the low bits on
            # large graphs). So jit the spec's functions BARE — the
            # identical executable the sequential loop compiles — and
            # move the lane axis eagerly, outside the compiled program
            self._jit_step = jax.jit(
                spec.train_step, donate_argnums=(0,),
                compiler_options=getattr(spec, "compiler_options",
                                         None))
            self._jit_eval = jax.jit(spec.eval_lane)

            def _sq(t):
                return jax.tree_util.tree_map(lambda a: a[0], t)

            def _ex(t):
                return jax.tree_util.tree_map(lambda a: a[None], t)

            def step_fn(state, hp, batch):
                s, loss = self._jit_step(_sq(state), _sq(hp),
                                         _sq(batch))
                return _ex(s), _ex(loss)

            def eval_fn(state, hp, batch):
                return _ex(self._jit_eval(_sq(state), _sq(hp), batch))

            self.step = step_fn
            self.eval_step = eval_fn
        else:
            self._jit_step = jax.jit(
                jax.vmap(spec.train_step, in_axes=(0, 0, 0)),
                donate_argnums=(0,),
                # the spec's searchable schedule (e.g. async-collective
                # overlap); static per bucket, so no extra compiles
                compiler_options=getattr(spec, "compiler_options",
                                         None))
            self._jit_eval = jax.jit(
                jax.vmap(spec.eval_lane, in_axes=(0, 0, None)))
            self.step = self._jit_step
            self.eval_step = self._jit_eval
        self.state: Any = None
        self.hp: Dict[str, Any] = {
            n: jnp.zeros((gang_size,), jnp.float32) for n in spec.hp_names}

    def _lane_hp(self, knobs: Knobs) -> Dict[str, Any]:
        return {n: self._jnp.float32(float(knobs[n]))
                for n in self.spec.hp_names}

    def fill_lane(self, i: int, knobs: Knobs,
                  warm_blob: Optional[dict]) -> None:
        """(Re)initialize lane ``i`` — fresh params/optimizer, optionally
        warm-started from a completed trial's blob — and write its
        traceable knob values into the hp arrays. Eager ops only: a
        refill never recompiles."""
        import jax

        lane_hp = self._lane_hp(knobs)
        lane = self.spec.init_lane(jax.random.PRNGKey(0), lane_hp)
        if warm_blob is not None:
            lane = self.spec.warm_lane(lane, warm_blob)
        if self.state is None:
            # first fill: broadcast lane 0's structure to K lanes
            self.state = jax.tree_util.tree_map(
                lambda a: self._jnp.broadcast_to(
                    a[None], (self.k,) + a.shape).copy(), lane)
        self.state = jax.tree_util.tree_map(
            lambda s, v: s.at[i].set(v), self.state, lane)
        for n, v in lane_hp.items():
            self.hp[n] = self.hp[n].at[i].set(v)

    def run_epoch(self, lane_epochs: List[int]) -> Tuple[int, int]:
        """Step every lane through one epoch of its OWN batch schedule
        (lane i's batches come from ``epoch_batches(lane_epochs[i])``).
        Returns (steps, samples-per-lane) for throughput accounting."""
        iters = [self.spec.epoch_batches(e) for e in lane_epochs]
        steps = samples = 0
        for per_lane in zip(*iters):
            batch = {key: np.stack([b[key] for b in per_lane])
                     for key in per_lane[0]}
            self.state, _loss = self.step(self.state, self.hp, batch)
            steps += 1
            samples += int(per_lane[0]["mask"].sum())
        return steps, samples

    def scores(self) -> np.ndarray:
        """Per-lane score over the validation stream — the vmapped twin
        of the template's ``evaluate``. ``score_kind="lm"`` lanes score
        inverse perplexity ``exp(-sum/count)``; the default is masked
        accuracy."""
        if getattr(self.spec, "score_kind", "accuracy") == "lm":
            # accumulate exactly as the LM template's evaluate() does:
            # float64 (== python float) sums over the SAME padded batch
            # stream, so a lane's score is bit-for-bit its sequential
            # twin's
            eval_seq = getattr(self.spec, "eval_seq", None)
            if eval_seq is not None:
                # per-lane on the sequential evaluate() graph — eval is
                # a sliver of lane wall-clock, and this is where the
                # exact-score contract is settled (a vmapped eval fuses
                # the forward differently and drifts in the low bits)
                import jax
                out = np.zeros(self.k)
                for i in range(self.k):
                    lane = jax.tree_util.tree_map(lambda a: a[i],
                                                  self.state)
                    hp = {n: self.hp[n][i] for n in self.spec.hp_names}
                    total = count = 0.0
                    for eb in self.spec.eval_batches():
                        s, c = eval_seq(lane, hp, eb)
                        total += float(s)
                        count += float(c)
                    out[i] = np.exp(-total / max(count, 1.0))
                return out
            totals = np.zeros(self.k)
            counts = np.zeros(self.k)
            for eb in self.spec.eval_batches():
                s, c = self.eval_step(self.state, self.hp, eb)
                totals += np.asarray(s, np.float64)
                counts += np.asarray(c, np.float64)
            return np.exp(-totals / np.maximum(counts, 1.0))
        correct = np.zeros(self.k)
        total = 0.0
        for eb in self.spec.eval_batches():
            preds = np.asarray(self.eval_step(self.state, self.hp,
                                              eb["x"]))
            mask = eb["mask"].astype(np.float64)
            correct += ((preds == np.asarray(eb["y"])[None, :])
                        * mask[None, :]).sum(axis=1)
            total += float(mask.sum())
        return correct / max(total, 1.0)

    def export(self, i: int) -> dict:
        import jax

        lane = jax.tree_util.tree_map(lambda a: np.asarray(a[i]),
                                      self.state)
        hp = {n: float(np.asarray(self.hp[n][i]))
              for n in self.spec.hp_names}
        return self.spec.export_blob(lane, hp)

    def compile_count(self) -> int:
        """Distinct train-step executables this bucket compiled (1 when
        every trial shape-matched the bucket, which is the invariant
        tier-1 asserts)."""
        try:
            return int(self._jit_step._cache_size())
        except Exception:  # rafiki: noqa[silent-except]
            return -1  # cache introspection is jax-version-dependent


class GangEngine:
    """Drives an advisor's propose/feedback cycle over gang-compiled
    lanes (``mode="gang"``) or the template's ordinary per-trial path on
    the same schedule (``mode="sequential"`` — the process-mode
    equivalence baseline).
    """

    #: completed-trial param snapshots kept for warm starts (LRU)
    MAX_BLOBS = 64

    def __init__(self, model_class: Type[BaseModel], advisor: Any,
                 train_dataset_path: str, val_dataset_path: str,
                 gang_size: int = 8, mode: str = "gang",
                 knob_overrides: Optional[Dict[str, Any]] = None,
                 metrics: Optional[Any] = None,
                 keep_blobs: bool = True,
                 on_result: Optional[Any] = None,
                 admission_check: Optional[Any] = None) -> None:
        if mode not in ("gang", "sequential"):
            raise ValueError(f"unknown gang mode {mode!r}")
        if gang_size < 1:
            raise ValueError("gang_size must be >= 1")
        if mode == "gang" and not supports_gang(model_class):
            raise ValueError(
                f"{model_class.__name__} has no make_gang_spec/gang_epochs;"
                " use mode='sequential' or tune_model's fallback")
        self.model_class = model_class
        self.advisor = advisor
        self.train_dataset_path = train_dataset_path
        self.val_dataset_path = val_dataset_path
        self.gang_size = int(gang_size)
        self.mode = mode
        self.knob_config = model_class.get_knob_config()
        self.knob_overrides = dict(knob_overrides or {})
        validate_override_keys(self.knob_config, self.knob_overrides,
                               context="knob_overrides")
        self.hp_names = traceable_knobs(self.knob_config)
        self.keep_blobs = keep_blobs
        self.on_result = on_result  # callable(TrialResult, blob) or None
        #: ``(knobs, gang_size) -> Optional[str]`` — a refusal reason
        #: (e.g. the worker's HBM admission verdict) or None to admit.
        #: A refused bucket runs its trials sequentially, visibly.
        self.admission_check = admission_check
        self.results: List[TrialResult] = []
        self._pending: List[Proposal] = []
        self._seen_buckets: set = set()
        self._blocked_buckets: Dict[str, str] = {}  # bucket -> reason
        self._execs: "OrderedDict[str, _VmapExec]" = OrderedDict()
        self._blobs: "OrderedDict[str, dict]" = OrderedDict()
        self._t0: Optional[float] = None
        from ..obs import StatsMap

        self.stats = StatsMap({
            "trials_completed": 0, "trials_started": 0, "lanes_culled": 0,
            "promotions": 0, "warm_start_misses": 0, "epoch_rounds": 0,
            "buckets": 0, "samples": 0})
        self._max_trials: Optional[int] = None
        self._wire_metrics(metrics)

    # ---- obs plumbing ----
    def _wire_metrics(self, metrics: Optional[Any]) -> None:
        self._metrics = metrics  # per-lane gauges mint lazily by label
        if metrics is None:
            self._g_active = self._c_culled = self._g_tph = \
                self._g_sps = None
            return
        self._g_active = metrics.gauge(
            "gang_lanes_active",
            "gang lanes currently training a live trial")
        self._c_culled = metrics.counter(
            "gang_lanes_culled_total",
            "lanes whose trial exited a sub-full ASHA rung (culled in "
            "place; promotions return in a later refill)")
        self._g_tph = metrics.gauge(
            "trials_per_hour",
            "completed-trial throughput of the gang engine")
        self._g_sps = metrics.gauge(
            "gang_samples_per_s",
            "aggregate training samples/s across all lanes")

    def _publish_lane_gauges(self, exec_: "_VmapExec",
                             lanes: List[Optional[Proposal]],
                             samples: int, dt: float) -> None:
        """Per-lane throughput gauges for LM gangs: ``lane_tokens_per_s``
        and ``lane_est_mfu`` (6·N·tokens/s over the host's aggregate
        peak), labeled ``lane=<i>`` so the Prometheus exposition shows
        every lane; idle lanes read 0. Specs without token accounting
        (``tokens_per_sample == 0``) skip both."""
        tokens = int(getattr(exec_.spec, "tokens_per_sample", 0) or 0)
        if self._metrics is None or not tokens:
            return
        tps = samples * tokens / dt  # every active lane steps together
        n_params = int(getattr(exec_.spec, "lane_param_count", 0) or 0)
        peak = 0.0
        if n_params:
            from ..worker.train import _device_peak_flops
            import jax

            devs = jax.local_devices()
            peak = _device_peak_flops(devs) * len(devs)
        for i, p in enumerate(lanes):
            lane_tps = tps if p is not None else 0.0
            self._metrics.gauge(
                "lane_tokens_per_s",
                "training tokens/s of one gang lane (0 when idle)",
                labels={"lane": str(i)}).set(lane_tps)
            if n_params and peak > 0:
                self._metrics.gauge(
                    "lane_est_mfu",
                    "estimated MFU of one gang lane "
                    "(6*params*tokens_per_s / aggregate peak FLOP/s)",
                    labels={"lane": str(i)}).set(
                        6.0 * n_params * lane_tps / peak)

    def _publish(self, active: int) -> None:
        if self._g_active is not None:
            self._g_active.set(active)
        if self._g_tph is not None and self._t0 is not None:
            dt = max(time.monotonic() - self._t0, 1e-9)
            self._g_tph.set(self.stats["trials_completed"] / dt * 3600.0)

    # ---- proposal plumbing ----
    def _bucket_of(self, p: Proposal) -> str:
        return static_signature(self.knob_config, p.knobs)

    def _remaining_starts(self) -> Optional[int]:
        if self._max_trials is None:
            return None
        return max(0, self._max_trials
                   - int(self.stats["trials_started"]))

    def _take_pending(self, bucket: str, n: int) -> List[Proposal]:
        """Pop up to ``n`` pending proposals matching ``bucket``,
        preserving arrival order; tops up from the advisor when pending
        runs dry (non-matching new proposals are queued, not dropped).
        Capped by the caller's ``max_trials`` budget — every proposal
        returned here is about to start a lane."""
        remaining = self._remaining_starts()
        if remaining is not None:
            n = min(n, remaining)
        out: List[Proposal] = []
        rest: List[Proposal] = []
        for p in self._pending:
            if len(out) < n and self._bucket_of(p) == bucket:
                out.append(p)
            else:
                rest.append(p)
        self._pending = rest
        if len(out) < n:
            # ONE top-up pull: a refill comes from the advisor's next
            # batch or not at all — hunting further would drain the
            # whole trial budget into the pending queue whenever the
            # advisor fragments across buckets
            for p in self.advisor.propose_batch(n - len(out)):
                self._apply_overrides(p)
                if len(out) < n and self._bucket_of(p) == bucket:
                    out.append(p)
                else:
                    self._pending.append(p)
        return out

    def _apply_overrides(self, p: Proposal) -> None:
        if self.knob_overrides:
            p.knobs = {**p.knobs, **self.knob_overrides}
        self.model_class.validate_knobs(p.knobs)

    def _epochs_for(self, p: Proposal) -> int:
        return int(self.model_class.gang_epochs(p.knobs, p.budget_scale)) \
            if supports_gang(self.model_class) else 1

    def _warm_blob(self, p: Proposal,
                   share_knob: Optional[str]) -> Optional[dict]:
        """The parent blob a refill warm-starts from, mirroring the
        sequential gate: a warm_start ref only applies when the
        template's SHARE_PARAMS knob is on for this proposal. A miss
        (parent evicted from the bounded LRU, or minted by another gang
        worker sharing this advisor) cold-starts the lane — VISIBLY, so
        an unexpectedly slow high rung is diagnosable."""
        if not p.warm_start_trial_id:
            return None
        if share_knob is not None and not p.knobs.get(share_knob):
            return None
        blob = self._blobs.get(p.warm_start_trial_id)
        if blob is None:
            self.stats.inc("warm_start_misses")
            import logging

            logging.getLogger(__name__).warning(
                "gang warm start %r for trial %d not in the blob cache "
                "(evicted or foreign worker); lane cold-starts",
                p.warm_start_trial_id, p.trial_no)
        return blob

    def _record(self, p: Proposal, score: float, blob: dict) -> None:
        trial_id = f"gang-{p.trial_no}"
        if self.keep_blobs:
            self._blobs[trial_id] = blob
            while len(self._blobs) > self.MAX_BLOBS:
                self._blobs.popitem(last=False)
        result = TrialResult(
            trial_no=p.trial_no, knobs=p.knobs, score=float(score),
            trial_id=trial_id, budget_scale=p.budget_scale, meta=p.meta)
        self.results.append(result)
        self.stats.inc("trials_completed")
        if p.meta.get("parent_trial_no") is not None:
            self.stats.inc("promotions")
        if p.budget_scale < 1.0 - 1e-9:
            self.stats.inc("lanes_culled")
            if self._c_culled is not None:
                self._c_culled.inc()
        if self.on_result is not None:
            self.on_result(result, blob)

    # ---- the run loop ----
    def run(self, max_trials: Optional[int] = None) -> List[TrialResult]:
        """Pull batched proposals until the advisor's budget — or
        ``max_trials`` — is spent; returns one TrialResult per
        lane-trial (also fed back to the advisor, in completion order).
        The cap bounds trials STARTED, enforced on every lane fill (not
        just between bucket sessions)."""
        self._t0 = time.monotonic()
        self._max_trials = max_trials
        while True:
            remaining = self._remaining_starts()
            if remaining is not None and remaining <= 0:
                break
            if not self._pending:
                k = self.gang_size if remaining is None \
                    else min(self.gang_size, remaining)
                batch = self.advisor.propose_batch(k)
                if not batch:
                    break
                for p in batch:
                    self._apply_overrides(p)
                self._pending.extend(batch)
            bucket = self._bucket_of(self._pending[0])
            self._run_session(bucket)
        if self._pending:
            # proposals pulled but never laned (cap hit / bucket
            # stranded at budget end): release the advisor's
            # outstanding slots so its `finished` can turn true
            for p in self._pending:
                try:
                    self.advisor.trial_errored(p.trial_no)
                except Exception:  # rafiki: noqa[silent-except]
                    pass  # advisor may already be gone at teardown
            self._pending.clear()
        self._publish(active=0)
        return self.results

    def _run_session(self, bucket: str) -> None:
        """Run one gang over one static bucket until every lane drains
        (culled lanes refill in place from the advisor's next batch;
        lanes idle out when the next proposals belong to other
        buckets)."""
        lanes: List[Optional[Proposal]] = [None] * self.gang_size
        epochs_left = [0] * self.gang_size
        lane_epoch = [0] * self.gang_size
        initial = self._take_pending(bucket, self.gang_size)
        if not initial:
            return
        exec_ = self._get_exec(bucket, initial[0].knobs)
        self._seen_buckets.add(bucket)
        self.stats.set("buckets", len(self._seen_buckets))
        for i, p in enumerate(initial):
            self._fill(exec_, i, p, lanes, epochs_left, lane_epoch)
        try:
            while any(p is not None for p in lanes):
                self._session_round(bucket, exec_, lanes, epochs_left,
                                    lane_epoch)
        except Exception:
            # a template bug fails the whole gang; release the advisor's
            # outstanding slots so the budget is not stranded
            for p in lanes:
                if p is not None:
                    try:
                        self.advisor.trial_errored(p.trial_no)
                    except Exception:  # rafiki: noqa[silent-except]
                        pass  # advisor may be gone; original error wins
            raise

    def _session_round(self, bucket: str, exec_: Optional[_VmapExec],
                       lanes: List[Optional[Proposal]],
                       epochs_left: List[int],
                       lane_epoch: List[int]) -> None:
        """One epoch round: step active lanes, then eval / feedback /
        refill the lanes whose trial budget just drained."""
        t_round = time.monotonic()
        if exec_ is not None:
            # inactive lanes step a dummy schedule (epoch 0); their
            # state is ignored and overwritten on refill
            _steps, samples = exec_.run_epoch(
                [lane_epoch[i] if lanes[i] is not None else 0
                 for i in range(self.gang_size)])
            n_active = sum(p is not None for p in lanes)
            self.stats.inc("samples", samples * n_active)
            dt_round = max(time.monotonic() - t_round, 1e-9)
            if self._g_sps is not None:
                self._g_sps.set(samples * n_active / dt_round)
            self._publish_lane_gauges(exec_, lanes, samples, dt_round)
        self.stats.inc("epoch_rounds")
        finished: List[int] = []
        for i, p in enumerate(lanes):
            if p is None:
                continue
            lane_epoch[i] += 1
            epochs_left[i] -= 1
            if epochs_left[i] <= 0:
                finished.append(i)
        if not finished:
            self._publish(sum(p is not None for p in lanes))
            return
        scores = exec_.scores() if exec_ is not None else None
        batch_results: List[TrialResult] = []
        for i in finished:
            p = lanes[i]
            if exec_ is not None:
                score, blob = float(scores[i]), exec_.export(i)
            else:
                score, blob = self._run_sequential_trial(p)
            self._record(p, score, blob)
            batch_results.append(self.results[-1])
            lanes[i] = None
        self.advisor.feedback_batch(batch_results)
        refills = self._take_pending(bucket, len(finished))
        for i, p in zip(finished, refills):
            self._fill(exec_, i, p, lanes, epochs_left, lane_epoch)
        self._publish(sum(p is not None for p in lanes))

    def _fill(self, exec_: Optional[_VmapExec], i: int, p: Proposal,
              lanes: List[Optional[Proposal]], epochs_left: List[int],
              lane_epoch: List[int]) -> None:
        lanes[i] = p
        self.stats.inc("trials_started")
        epochs_left[i] = max(1, self._epochs_for(p))
        lane_epoch[i] = 0
        if exec_ is not None:
            share = exec_.spec.share_params_knob
            exec_.fill_lane(i, p.knobs, self._warm_blob(p, share))

    def _gang_refusal(self, knobs: Knobs) -> Optional[str]:
        """Why this bucket cannot run as vmapped lanes (None = it can):
        the template's NAMED ``gang_blockers`` first (which knob pins
        the config to the sequential mesh path), then the caller's
        admission check (the worker's HBM budget verdict, which sees
        ``remat_policy`` trade activations for recompute)."""
        blockers_fn = getattr(self.model_class, "gang_blockers", None)
        if callable(blockers_fn):
            blockers = blockers_fn(knobs)
            if blockers:
                return "knobs block gang lanes: " + "; ".join(blockers)
        if self.admission_check is not None:
            return self.admission_check(knobs, self.gang_size)
        return None

    def _get_exec(self, bucket: str,
                  rep_knobs: Knobs) -> Optional[_VmapExec]:
        if self.mode == "sequential":
            return None
        if bucket in self._blocked_buckets:
            return None
        exec_ = self._execs.get(bucket)
        if exec_ is None:
            reason = self._gang_refusal(rep_knobs)
            if reason is not None:
                self._blocked_buckets[bucket] = reason
                import logging

                logging.getLogger(__name__).warning(
                    "gang bucket falls back to sequential trials: %s",
                    reason)
                return None
            spec = self.model_class.make_gang_spec(
                dict(rep_knobs), self.train_dataset_path,
                self.val_dataset_path)
            if list(spec.hp_names) != list(self.hp_names):
                raise ValueError(
                    f"gang spec hp_names {list(spec.hp_names)} != "
                    f"traceable knobs {self.hp_names}")
            exec_ = _VmapExec(spec, self.gang_size)
            self._execs[bucket] = exec_
        return exec_

    # ---- sequential (process-mode) executor ----
    def _run_sequential_trial(self, p: Proposal) -> Tuple[float, dict]:
        """The template's ordinary per-trial path on the gang schedule:
        what one process-per-trial worker would compute for this
        proposal (warm start included)."""
        model = self.model_class(**p.knobs)
        shared = self._blobs.get(p.warm_start_trial_id) \
            if p.warm_start_trial_id else None
        ctx = TrainContext(logger=ModelLogger(),
                           budget_scale=p.budget_scale,
                           shared_params=shared,
                           trial_id=f"gang-{p.trial_no}")
        model.train(self.train_dataset_path, ctx)
        score = float(model.evaluate(self.val_dataset_path))
        import jax

        blob = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x,
            model.dump_parameters())
        model.destroy()
        return score, blob

    # ---- introspection (tier-1 compile-count assertions) ----
    def compile_counts(self) -> Dict[str, int]:
        """Per-bucket count of distinct train-step executables."""
        return {b: e.compile_count() for b, e in self._execs.items()}

    @property
    def n_buckets(self) -> int:
        return len(self._execs)

    @property
    def trials_per_hour(self) -> float:
        if self._t0 is None:
            return 0.0
        dt = max(time.monotonic() - self._t0, 1e-9)
        return self.stats["trials_completed"] / dt * 3600.0
