"""Minimal JSON-over-HTTP service kit (stdlib only).

The reference's services speak Flask REST between containers (SURVEY.md §3,
§5.8). Flask isn't in this image, so this module provides the same
ergonomics on ``http.server``: a route table of
``(method, path_pattern) -> handler(match, body_json, headers) -> (status,
json)`` served by a threading server. Path patterns use ``<name>``
segments, e.g. ``/train_jobs/<id>/stop``.
"""

from __future__ import annotations

import json
import re
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

Handler = Callable[[Dict[str, str], Any, Dict[str, str]],
                   Tuple[int, Any]]


class RawResponse:
    """Non-JSON handler payload (static HTML/JS for the dashboard)."""

    def __init__(self, data: bytes, content_type: str) -> None:
        self.data = data
        self.content_type = content_type


class StreamResponse:
    """Incremental handler payload: an iterator of already-encoded
    chunks written to the socket as they are produced (server-sent
    events for streaming generation). The connection closes when the
    iterator ends — ``Connection: close`` instead of chunked framing
    keeps the client side a dumb line reader."""

    def __init__(self, chunks: Any,
                 content_type: str = "text/event-stream") -> None:
        self.chunks = chunks  # iterator of bytes
        self.content_type = content_type


def _compile(pattern: str) -> re.Pattern:
    regex = re.sub(r"<([a-zA-Z_][a-zA-Z0-9_]*)>", r"(?P<\1>[^/]+)", pattern)
    return re.compile("^" + regex + "$")


class JsonHttpService:
    """A threading HTTP server over a JSON route table.

    Handlers receive the path-pattern groups MERGED with URL query
    parameters (path segments win on a name clash), the parsed JSON
    body, and the request headers.

    ``registry`` (a duck-typed ``rafiki_tpu.obs.MetricsRegistry``)
    auto-instruments every surface that passes one: a
    ``http_requests_total`` counter and an ``http_request_seconds``
    handler-latency histogram — the time INSIDE the handler, so a
    long-lived SSE stream does not read as one enormous request.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Any = None) -> None:
        self._routes: List[Tuple[str, re.Pattern, Handler]] = []
        self._host = host
        self._port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._req_counter = None
        self._req_hist = None
        if registry is not None:
            self._req_counter = registry.counter(
                "http_requests_total", "HTTP requests served")
            self._req_hist = registry.histogram(
                "http_request_seconds", "handler latency (seconds)")

    def route(self, method: str, pattern: str, handler: Handler) -> None:
        self._routes.append((method.upper(), _compile(pattern), handler))

    # ---- lifecycle ----
    def start(self) -> Tuple[str, int]:
        routes = self._routes
        req_counter, req_hist = self._req_counter, self._req_hist

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: Any) -> None:
                pass  # quiet; service logs go through the app layer

            def _dispatch(self, method: str) -> None:
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    body = json.loads(raw) if raw else None
                except Exception:
                    self._reply(400, {"error": "malformed JSON body"})
                    return
                path, _, query = self.path.partition("?")
                for m, pat, handler in routes:
                    if m != method:
                        continue
                    match = pat.match(path)
                    if match:
                        params = match.groupdict()
                        if query:
                            from urllib.parse import parse_qsl

                            for k, v in parse_qsl(query):
                                # path segments win: a ?id=... must not
                                # shadow a /things/<id> capture
                                params.setdefault(k, v)
                        import time as _time

                        t0 = _time.monotonic()
                        try:
                            status, payload = handler(
                                params, body,
                                dict(self.headers.items()))
                        except _HttpError as e:
                            status, payload = e.status, {"error": e.message}
                        except Exception:
                            status = 500
                            payload = {"error": "internal error",
                                       "detail": traceback.format_exc(
                                           limit=5)}
                        if req_counter is not None:
                            req_counter.inc()
                            req_hist.observe(_time.monotonic() - t0)
                        self._reply(status, payload)
                        return
                self._reply(404, {"error": f"no route {method} {path}"})

            def _reply(self, status: int, payload: Any) -> None:
                if isinstance(payload, StreamResponse):
                    self.send_response(status)
                    self.send_header("Content-Type", payload.content_type)
                    self.send_header("Cache-Control", "no-store")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.close_connection = True
                    try:
                        for chunk in payload.chunks:
                            self.wfile.write(chunk)
                            self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        pass  # client went away mid-stream
                    return
                if isinstance(payload, RawResponse):  # e.g. dashboard HTML
                    data, ctype = payload.data, payload.content_type
                else:
                    data = json.dumps(payload).encode("utf-8")
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                self._dispatch("GET")

            def do_POST(self) -> None:
                self._dispatch("POST")

            def do_PUT(self) -> None:
                self._dispatch("PUT")

            def do_DELETE(self) -> None:
                self._dispatch("DELETE")

        self._server = ThreadingHTTPServer((self._host, self._port), _Handler)
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._host, self._port

    def serve_forever(self) -> None:
        """Blocking variant for service main()s."""
        if self._server is None:
            self.start()
        assert self._thread is not None
        self._thread.join()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def port(self) -> int:
        return self._port


class _HttpError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


def http_error(status: int, message: str) -> _HttpError:
    return _HttpError(status, message)


# ---- client side -----------------------------------------------------------

class HttpStatusError(RuntimeError):
    """A non-2xx HTTP response, with the status code and decoded JSON
    payload attached. Subclasses RuntimeError so every existing caller
    that catches the old convention keeps working; new callers (the
    client SDK's structured-503 retry) can inspect ``status`` and
    ``payload`` (e.g. ``payload.get("retry_after_s")``) instead of
    parsing the message string."""

    def __init__(self, method: str, url: str, status: int,
                 payload: Any) -> None:
        detail = payload.get("error", payload) \
            if isinstance(payload, dict) else payload
        super().__init__(f"{method} {url} -> {status}: {detail}")
        self.status = int(status)
        self.payload = payload if isinstance(payload, dict) else {}

    @property
    def shed(self) -> bool:
        """True for an SLO *shed* 503 (overload backpressure on a
        best-effort class: honor ``retry_after_s`` and come back) as
        opposed to a breaker fast-fail 503 (the fleet is down/dead —
        retrying sooner than its ``retry_after_s`` probes the same
        outage). Both carry ``retry_after_s``; only sheds carry
        ``shed: true``."""
        return bool(self.payload.get("shed"))

    @property
    def data_plane_down(self) -> bool:
        """True for the data-plane-down 503: the predictor could not
        reach the kvd (param blobs + queues). Shed-like semantics —
        the supervisor respawns the kvd with WAL replay in seconds, so
        honoring ``retry_after_s`` and retrying once is expected to
        succeed."""
        return bool(self.payload.get("data_plane_down"))

    @property
    def retry_after_s(self) -> Optional[float]:
        """The server's structured retry hint, when present and
        numeric."""
        v = self.payload.get("retry_after_s")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        return None


def _open_request(method: str, url: str, body: Any,
                  headers: Optional[Dict[str, str]], timeout: float,
                  accept: Optional[str] = None):
    """Open a JSON-bodied request, translating HTTPError into
    :class:`HttpStatusError` (a RuntimeError, the convention shared by
    every client in this repo). Returns the live response object
    (caller closes)."""
    import urllib.error
    import urllib.request

    data = json.dumps(body).encode("utf-8") if body is not None else None
    req = urllib.request.Request(url, data=data, method=method.upper())
    req.add_header("Content-Type", "application/json")
    if accept:
        req.add_header("Accept", accept)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        return urllib.request.urlopen(req, timeout=timeout)
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            payload = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            payload = {"error": raw.decode("utf-8", "replace")}
        raise HttpStatusError(method, url, e.code, payload) from None


def json_request(method: str, url: str, body: Any = None,
                 headers: Optional[Dict[str, str]] = None,
                 timeout: float = 30.0) -> Any:
    """Tiny JSON HTTP client (urllib; no external deps in the hot path)."""
    with _open_request(method, url, body, headers, timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


#: default whole-stream budget for SSE generation streams. Lives here —
#: next to the transport both ends use — so the client SDK can size its
#: per-event socket timeout without importing the server-side predictor
#: module (Predictor.STREAM_TIMEOUT aliases this).
STREAM_BUDGET_S = 300.0


def sse_request(method: str, url: str, body: Any = None,
                headers: Optional[Dict[str, str]] = None,
                timeout: float = 30.0,
                read_timeout: Optional[float] = None):
    """Yield decoded JSON payloads from a server-sent-events endpoint.

    Matches the minimal SSE dialect :class:`StreamResponse` producers
    emit: ``data: <json>\\n\\n`` per event, connection close = end of
    stream. ``timeout`` bounds connection establishment (and each event
    wait unless ``read_timeout`` is given); ``read_timeout`` bounds the
    wait for EACH event once the stream is up — a generation may
    legitimately idle near the server's whole-stream budget, but a down
    host must still fail fast at connect time."""
    resp = _open_request(method, url, body, headers, timeout,
                         accept="text/event-stream")
    try:
        if read_timeout is not None and read_timeout != timeout:
            # the urlopen timeout rode onto the connected socket; now
            # that the response is live, re-bound it for event reads.
            # CPython: HTTPResponse.fp is a buffered reader over a
            # SocketIO holding the raw socket — reach it defensively
            # (the else-branch below keeps the long bound on any
            # non-CPython/refactored layout)
            sock = getattr(getattr(resp, "fp", None), "raw", None)
            sock = getattr(sock, "_sock", None)  # rafiki: noqa[library-internals] — fallback below
            if hasattr(sock, "settimeout"):
                sock.settimeout(read_timeout)
            else:
                # introspection failed (non-CPython, internals
                # refactor): reads would stay bounded by the SHORT
                # connect timeout and a legitimately idle generation
                # would die mid-stream. Fall back to the
                # pre-introspection behavior — re-open the request
                # with the long bound as the socket timeout for the
                # whole stream. No event has been consumed yet, and a
                # duplicated request beats a stream that cannot run
                # longer than the connect bound.
                import logging

                logging.getLogger(__name__).warning(
                    "sse_request could not re-bound the socket for "
                    "event reads (HTTPResponse internals changed?); "
                    "re-opening the stream with the %.0fs bound for "
                    "the whole request", max(timeout, read_timeout))
                resp.close()
                resp = _open_request(method, url, body, headers,
                                     max(timeout, read_timeout),
                                     accept="text/event-stream")
        for line in resp:  # socket timeout applies per readline
            line = line.strip()
            if line.startswith(b"data:"):
                yield json.loads(line[5:].strip().decode("utf-8"))
    finally:
        resp.close()
