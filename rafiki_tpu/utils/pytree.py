"""Shared nested-dict pytree helpers (checkpoint + weight-import use).

One implementation so the safetensors importer (models/convert.py) and
the sharded checkpointer (store/sharded_ckpt.py) can never drift on
traversal order or container support: plain dicts (and flax FrozenDict,
which duck-types as a Mapping) in sorted-key order.
"""

from __future__ import annotations

from typing import Any, Iterator, Tuple


def leaf_paths(tree: Any,
               prefix: Tuple[str, ...] = ()) -> Iterator[
                   Tuple[Tuple[str, ...], Any]]:
    """Yield (path, leaf) in deterministic sorted-key order."""
    if hasattr(tree, "items"):  # dict / FrozenDict
        for k in sorted(tree):
            yield from leaf_paths(tree[k], prefix + (str(k),))
    else:
        yield prefix, tree


def flatten_paths(tree: Any) -> dict:
    return dict(leaf_paths(tree))


def set_path(tree: Any, path: Tuple[str, ...], value: Any) -> None:
    """In-place assignment at ``path`` (the tree must be mutable dicts)."""
    node = tree
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value
