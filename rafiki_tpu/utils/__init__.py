"""Shared utilities: JSON-HTTP service kit, serialization, ids."""

from .http import JsonHttpService, http_error, json_request

__all__ = ["JsonHttpService", "http_error", "json_request"]
