"""Platform pinning for spawned service processes.

The TPU-VM image may register an accelerator PJRT plugin at interpreter
start (sitecustomize) and pin ``JAX_PLATFORMS`` in the environment, so a
child that should run on CPU (tests, control-plane probes) cannot rely on
env vars alone — it must override via ``jax.config`` before any backend
initializes. Service entrypoints call :func:`apply_platform_env` first.
"""

from __future__ import annotations

import os
from typing import Optional

#: set by the ServicesManager on children: "cpu" | "tpu" | "" (inherit)
PLATFORM_ENV = "RAFIKI_JAX_PLATFORM"

#: persistent XLA-executable cache shared by all service processes. Trials
#: are separate processes but overwhelmingly compile the SAME programs
#: (same template, same shape-relevant knobs across rungs/replicas), so a
#: disk cache turns every repeat compile into a load — this is the
#: "cache compiled executables by shape-signature" obligation from
#: SURVEY.md §7. Override/disable with RAFIKI_COMPILE_CACHE=path|off.
CACHE_ENV = "RAFIKI_COMPILE_CACHE"


def compile_cache_path() -> Optional[str]:
    """The resolved persistent-compile-cache directory, or None when
    disabled via ``RAFIKI_COMPILE_CACHE=off``. Single source of truth
    for the env name and the default path (``apply_platform_env`` and
    the doctor both resolve through here)."""
    cache = os.environ.get(CACHE_ENV, "")
    if cache == "off":
        return None
    return os.path.expanduser(cache) if cache else os.path.join(
        os.path.expanduser("~"), ".cache", "rafiki_tpu", "xla_cache")


def apply_platform_env() -> str:
    """Apply platform + compile-cache config before jax backends init.

    Keeps the no-op path jax-free: numpy-only services (the predictor)
    call this too and must not pay a jax import for nothing.
    """
    platform = os.environ.get(PLATFORM_ENV, "")
    if platform and platform != "tpu":
        import jax

        jax.config.update("jax_platforms", platform)
    cache = compile_cache_path()
    if cache is not None:
        try:
            os.makedirs(cache, exist_ok=True)
        except OSError:
            return platform  # unwritable dir: run without the cache
        import sys

        if "jax" in sys.modules:  # already imported (e.g. sitecustomize):
            # env vars were read at import time — use config updates
            try:
                jax = sys.modules["jax"]
                jax.config.update("jax_compilation_cache_dir", cache)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.3)
            except AttributeError:
                pass  # older jax without these knobs
        else:  # defer via env: numpy-only services never pay a jax import
            os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
            os.environ.setdefault(
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")
    return platform
