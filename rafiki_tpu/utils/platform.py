"""Platform pinning for spawned service processes.

The TPU-VM image may register an accelerator PJRT plugin at interpreter
start (sitecustomize) and pin ``JAX_PLATFORMS`` in the environment, so a
child that should run on CPU (tests, control-plane probes) cannot rely on
env vars alone — it must override via ``jax.config`` before any backend
initializes. Service entrypoints call :func:`apply_platform_env` first.
"""

from __future__ import annotations

import os

#: set by the ServicesManager on children: "cpu" | "tpu" | "" (inherit)
PLATFORM_ENV = "RAFIKI_JAX_PLATFORM"


def apply_platform_env() -> str:
    """Apply the requested platform before jax backends initialize."""
    platform = os.environ.get(PLATFORM_ENV, "")
    if platform and platform != "tpu":
        import jax

        jax.config.update("jax_platforms", platform)
    return platform
