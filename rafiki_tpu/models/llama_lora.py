"""Llama-style decoder LM with LoRA fine-tuning — BASELINE.md config #5.

Parity target: benchmark config #5 ("Llama-3 8B LoRA fine-tune +
continuous-batch serving via Predictor"). TPU-first design notes:

- The decoder (RMSNorm → RoPE → GQA causal flash attention → SwiGLU) is a
  flax module whose training attention runs through the Pallas flash
  kernel with per-example ``kv_lens`` (packed ragged batches stay one
  static-shape tensor).
- **2-D (fsdp × tensor) sharding** via ``parallel.sharding``: attention
  and MLP projections are tensor-parallel over the mesh's ``model`` axis
  (wq/wk/wv/gate/up split on the output dim, wo/down on the input dim —
  the Megatron pairing, so XLA inserts exactly one all-reduce per block),
  everything large is additionally fsdp-sharded over ``data``. No
  hand-written collectives anywhere.
- **LoRA**: every projection carries frozen ``kernel`` plus trainable
  ``lora_a``/``lora_b``; freezing is an ``optax.masked`` transform (the
  idiomatic JAX equivalent of requires_grad=False), so the base stays
  untouched and checkpoints can ship adapters only.
- **Generation**: greedy decode over a flax ``cache`` collection carried
  through ``lax.scan`` — one compiled step regardless of output length.
  Prefill is per-token through the same step (correct and simple; chunked
  prefill is a serving-layer optimization).
- No pretrained weights exist in this zero-egress environment, so the
  "base" is random and LoRA+head training carries the learning signal;
  the architecture and sharding are what the 8B config exercises.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_text_classification_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, GangSpec, IntegerKnob, KnobConfig,
                              Knobs, PolicyKnob, TrainContext,
                              same_tree_shapes, train_epoch)
from rafiki_tpu.models.bert import _TOKEN_RE, PAD_ID, HashTokenizer
from rafiki_tpu.ops.attention import flash_attention
from rafiki_tpu.ops.paged_attention import (kv_cache_write,
                                            paged_decode_attention,
                                            paged_window_attention,
                                            resolve_paged_kernel,
                                            resolve_paged_window_kernel)
from rafiki_tpu.parallel.sharding import (DATA_AXIS, MODEL_AXIS,
                                          batch_sharding, make_mesh,
                                          overlap_compiler_options,
                                          param_shardings)

BOS_ID = 1  # reuse bert's CLS slot as BOS

#: Megatron-style tensor-parallel rules: column-parallel projections split
#: the output dim, row-parallel ones the input dim → one all-reduce per
#: attention/MLP block. Keys match LoRADense instance names below.
#: "experts" shards stacked MoE expert weights on their EXPERT dim —
#: expert parallelism: each model-axis device owns E/mp experts and XLA
#: schedules the token all-to-all around them (ops/moe.py).
#: NOTE: first matching rule wins and "gate"/"up"/"down" are substrings
#: of the stacked expert names — "experts" must stay first.
TP_RULES = {"experts": 0,
            "wq": -1, "wk": -1, "wv": -1, "gate": -1, "up": -1,
            "wo": 0, "down": 0, "lm_head": -1, "tok_embed": -1}


def rope(x: jnp.ndarray, positions: jnp.ndarray,
         theta: float = 10000.0,
         scaling: Optional[Tuple[float, float, float, float]] = None
         ) -> jnp.ndarray:
    """Rotary embedding over (b, s, heads, head_dim) with (b, s)
    positions.

    ``scaling`` applies Llama-3.1-style frequency-dependent NTK
    scaling: ``(factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings)``. High-frequency components
    (wavelength ≪ the original context) keep their frequency, very
    low-frequency ones divide by ``factor``, and the band between
    interpolates smoothly — the published recipe for stretching a
    pretrained context window without retraining the short-range
    geometry."""
    half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if scaling is not None:
        factor, low_f, high_f, orig_len = scaling
        # ratio = original_context / wavelength (wavelength = 2π/freq)
        ratio = orig_len * freqs / (2.0 * np.pi)
        smooth = jnp.clip((ratio - low_f) / max(high_f - low_f, 1e-9),
                          0.0, 1.0)
        scaled = freqs / factor
        freqs = jnp.where(
            ratio < low_f, scaled,
            jnp.where(ratio > high_f, freqs,
                      (1.0 - smooth) * scaled + smooth * freqs))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (b, s, half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)


def _same_tokenizer(a: Any, b: Any) -> bool:
    """Do two tokenizers map ids to the same text? BPE tokenizers
    compare merge tables; otherwise same type + vocab (HashTokenizer
    is fully determined by its vocab size)."""
    if type(a) is not type(b):
        return False
    am, bm = getattr(a, "merges", None), getattr(b, "merges", None)
    if am is not None or bm is not None:
        return am == bm
    return a.vocab_size == b.vocab_size


def _parse_rope_scaling(value: Any
                        ) -> Optional[Tuple[float, float, float, float]]:
    """Knob value (JSON object string, dict, or "") → the static
    scaling tuple :func:`rope` consumes. HF config key names are
    accepted directly, with the published Llama-3.1 defaults for the
    optional band parameters."""
    if not value:
        return None
    if isinstance(value, str):
        import json as _json

        value = _json.loads(value)
    c = dict(value)
    kind = str(c.get("rope_type", c.get("type", "llama3"))).lower()
    if kind == "default":
        return None  # HF semantics: explicit 'default' = unscaled
    if kind != "llama3":
        # linear/dynamic/yarn use DIFFERENT position geometry;
        # applying the llama3 NTK-by-parts formula to them would be
        # silently wrong — refuse loudly instead
        raise ValueError(
            f"unsupported rope_scaling type {kind!r} (only 'llama3' "
            "frequency-dependent scaling is implemented)")
    if "factor" not in c:
        raise ValueError("rope_scaling requires a 'factor' key "
                         f"(got {sorted(c)})")
    return (float(c["factor"]),
            float(c.get("low_freq_factor", 1.0)),
            float(c.get("high_freq_factor", 4.0)),
            float(c.get("original_max_position_embeddings", 8192)))


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class LoRADense(nn.Module):
    """Frozen base kernel + trainable low-rank adapter (classic LoRA).

    ``quantized=True`` swaps the f32 base kernel for an int8 tensor plus
    per-output-channel f32 scales (symmetric absmax — see
    :func:`quantize_llama_params`). Serving-only post-training
    quantization: persistent weight HBM drops 4x and the decode loop —
    HBM-bandwidth-bound at batch 1..slots — reads a quarter of the
    bytes per step. Most kernels are the frozen LoRA bases (their
    trained signal lives in the f32 adapters); the trained ``lm_head``
    kernel is quantized too, with per-channel error ≤ absmax/254 —
    standard W8 PTQ, logits-closeness covered by tests. The int8
    operand feeds the matmul directly (one convert, the most fusable
    form) and the channel scale applies to the OUTPUT, never
    materializing a dequantized kernel; adapters/norms/embeddings stay
    full precision.
    """

    features: int
    rank: int = 0
    alpha: float = 16.0
    quantized: bool = False
    #: >0 — multi-adapter serving (S-LoRA-style): ``lora_a``/``lora_b``
    #: carry a leading adapter axis and every batch row applies ITS OWN
    #: adapter, selected by the per-row ``adapter_ids`` operand. The
    #: base matmul runs once for the whole batch (that's the point:
    #: N fine-tunes share one base's HBM and one MXU pass); only the
    #: rank-r correction is per-row, as two batched einsums over
    #: gathered (B, d, r)/(B, r, f) adapter slices — tiny vs the base.
    n_adapters: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 adapter_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        d_in = x.shape[-1]
        if self.quantized:
            qk = self.param("qkernel", nn.initializers.zeros,
                            (d_in, self.features), jnp.int8)
            qs = self.param("qscale", nn.initializers.ones,
                            (self.features,))
            # scale on the small (…, features) output, not the kernel:
            # (x @ q) * s == x @ (q * s) with b·f elementwise work
            # instead of d_in·f, and the dot consumes a bare int8→dtype
            # convert (fuses; no dequantized kernel ever materializes)
            y = (x @ qk.astype(x.dtype)) * qs.astype(x.dtype)
        else:
            kernel = self.param("kernel", nn.initializers.lecun_normal(),
                                (d_in, self.features))
            # compute in x's dtype (params stay f32): a bf16 activation
            # must not promote the matmul to f32 (~3x cost on the MXU)
            y = x @ kernel.astype(x.dtype)
        if self.rank > 0:
            if self.n_adapters > 0:
                a = self.param("lora_a", nn.initializers.normal(0.02),
                               (self.n_adapters, d_in, self.rank))
                b = self.param("lora_b", nn.initializers.zeros,
                               (self.n_adapters, self.rank, self.features))
                if adapter_ids is None:  # init trace / unselected call
                    adapter_ids = jnp.zeros((x.shape[0],), jnp.int32)
                asel = jnp.take(a, adapter_ids, axis=0).astype(x.dtype)
                bsel = jnp.take(b, adapter_ids, axis=0).astype(x.dtype)
                y = y + jnp.einsum(
                    "bsr,brf->bsf",
                    jnp.einsum("bsd,bdr->bsr", x, asel), bsel) * (
                        self.alpha / self.rank)
            else:
                a = self.param("lora_a", nn.initializers.normal(0.02),
                               (d_in, self.rank))
                b = self.param("lora_b", nn.initializers.zeros,
                               (self.rank, self.features))
                y = y + ((x @ a.astype(x.dtype)) @ b.astype(x.dtype)) * (
                    self.alpha / self.rank)
        return y


def _masked_decode_attention(q, kk, vv, t, dh: int, dtype) -> jnp.ndarray:
    """The decode branch's gather-path attention: (b, s, H, dh) queries
    over (b, length, H, dh) logical-order keys/values, each query token
    masked to keys at-or-before its own position. ``length`` follows
    the gathered view — on paged engines that is the live-width slice
    of the table (pages actually allocated), not ``max_len``, so the
    fallback stops touching dead pages."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(dh)
    k_pos = jnp.arange(kk.shape[1])[None, None, None, :]
    scores = jnp.where(k_pos <= t[:, None, :, None], scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(dtype), vv)


class _DecoderAttention(nn.Module):
    n_heads: int
    n_kv_heads: int
    max_len: int
    lora_rank: int
    quantized: bool = False
    n_adapters: int = 0
    #: sequence parallelism (train path): run the causal attention via
    #: ulysses all-to-alls over mesh[seq_axis], with the sequence dim of
    #: every activation sharded on that axis. Loss-exact WITHOUT kv_lens
    #: masking: causal attention means padded keys (beyond an example's
    #: length) are only visible to queries AT padded positions, whose
    #: loss terms are masked — valid positions' logits are untouched.
    seq_mesh: Any = None
    seq_axis: Optional[str] = None
    #: tensor-parallel composition: mesh axis the HEAD dim is sharded
    #: over (Megatron TP). The sp collectives then run within each TP
    #: head group — see ops/ulysses.py / ops/ring_attention.py.
    head_axis: Optional[str] = None
    rope_theta: float = 10000.0
    rope_scaling: Optional[Tuple[float, float, float, float]] = None
    #: serving-only int8 KV cache: K/V rows store as int8 with one f32
    #: absmax scale per (slot, position, kv-head) vector — half the
    #: decode cache's HBM at bf16 (4x at f32), bought with a bounded
    #: per-element quantization error (<= absmax/254 per component).
    #: Reads dequantize on the fly and fuse into the attention einsum.
    kv_int8: bool = False
    #: >0 — paged KV cache (serving decode path): per layer K/V live in
    #: a (kv_pages, kv_page_size, kv_heads, dh) POOL instead of per-slot
    #: (b, max_len, ...) rows; each batch row maps logical pages to pool
    #: pages via the ``page_tables`` call operand ((b, max_len/page)
    #: int32, host-owned). Cache HBM then scales with the pool — live
    #: tokens — not slots x max_len. Writes scatter at
    #: (table[pos // page], pos % page); attention gathers the row's
    #: pages back into logical order, so the masked softmax consumes
    #: exactly the bytes the contiguous layout would (bit-exact; garbage
    #: in unallocated pages sits past the position mask). int8-KV scale
    #: rows page identically. Pool page 0 is the engine's scratch page
    #: (idle lanes write there; never read unmasked).
    kv_page_size: int = 0
    kv_pages: int = 0
    #: paged decode dispatch (kv_page_size > 0 only): ``None`` (auto)
    #: runs the Pallas paged-attention kernels — which walk the block
    #: table directly instead of gathering pages back to logical order
    #: — on TPU and the page gather off-TPU; ``True``/``False`` force
    #: one path (tests force ``True``, riding the interpreter on CPU).
    #: EVERY decode call is kernel-eligible: the single-token step
    #: (s == 1, the generation hot loop) takes
    #: ``paged_decode_attention`` and multi-token windows (chunked
    #: prefill, speculative verify) take ``paged_window_attention``,
    #: which adds the in-window causal mask. Windows honor one extra
    #: operational escape hatch — ``RAFIKI_PAGED_KERNEL_WINDOWS=0``
    #: drops them back onto the gather (step-only mode) without
    #: touching the hot loop. See ``ops/paged_attention.py``.
    paged_kernel: Optional[bool] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, lens: jnp.ndarray,
                 positions: jnp.ndarray, decode: bool,
                 adapter_ids: Optional[jnp.ndarray] = None,
                 page_tables: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        b, s, d = x.shape
        dh = d // self.n_heads
        dense = functools.partial(LoRADense, rank=self.lora_rank,
                                  quantized=self.quantized,
                                  n_adapters=self.n_adapters)
        q = dense(self.n_heads * dh, name="wq")(x, adapter_ids)
        k = dense(self.n_kv_heads * dh, name="wk")(x, adapter_ids)
        v = dense(self.n_kv_heads * dh, name="wv")(x, adapter_ids)
        q = rope(q.reshape(b, s, self.n_heads, dh), positions,
                 theta=self.rope_theta, scaling=self.rope_scaling)
        k = rope(k.reshape(b, s, self.n_kv_heads, dh), positions,
                 theta=self.rope_theta, scaling=self.rope_scaling)
        v = v.reshape(b, s, self.n_kv_heads, dh)
        rep = self.n_heads // self.n_kv_heads

        if decode:
            # autoregressive path: write this step's k/v into each
            # example's OWN cache row at its OWN position (vectorized
            # scatter), then attend the single query over that example's
            # prefix. Per-slot positions are what continuous batching
            # needs — slots admitted mid-flight run at different depths
            # in the same compiled step. The flax init pass also traces
            # this branch — guard with has_variable so initialization
            # only allocates zeros and never writes.
            is_live = self.has_variable("cache", "k")
            kv_dtype = jnp.int8 if self.kv_int8 else x.dtype
            paged = self.kv_page_size > 0
            if paged:  # pool layout: pages, not per-slot rows
                kv_shape = (self.kv_pages, self.kv_page_size,
                            self.n_kv_heads, dh)
                sc_shape = (self.kv_pages, self.kv_page_size,
                            self.n_kv_heads)
            else:
                kv_shape = (b, self.max_len, self.n_kv_heads, dh)
                sc_shape = (b, self.max_len, self.n_kv_heads)
            ck = self.variable("cache", "k", jnp.zeros, kv_shape,
                               kv_dtype)
            cv = self.variable("cache", "v", jnp.zeros, kv_shape,
                               kv_dtype)
            if self.kv_int8:  # one absmax scale per stored K/V vector
                sk = self.variable("cache", "k_scale", jnp.zeros,
                                   sc_shape, jnp.float32)
                sv = self.variable("cache", "v_scale", jnp.zeros,
                                   sc_shape, jnp.float32)
            if not is_live:
                # init trace: local attention for output shape only
                kk = jnp.repeat(k, rep, axis=2)
                vv = jnp.repeat(v, rep, axis=2)
                scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(dh)
                probs = jax.nn.softmax(scores.astype(jnp.float32), -1)
                o = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(x.dtype), vv)
            else:
                # s >= 1: single-token generation AND chunked prefill ride
                # the same branch — write the chunk's k/v at each slot's
                # own positions (vectorized scatter), then mask each
                # QUERY token to keys at-or-before its own position.
                # Within-chunk causality falls out of the position mask:
                # the whole chunk is written before attention, and query
                # p only sees k_pos <= p. Duplicate positions in a row
                # (idle slots re-fed their current token) rewrite
                # identical values — harmless by construction.
                t = positions  # (b, s) — per-slot, per-token write index
                if paged:
                    if page_tables is None:
                        raise ValueError(
                            "kv_page_size > 0 decode requires the "
                            "page_tables operand (the serving engine "
                            "supplies it; plain generate paths must use "
                            "a contiguous-cache module)")
                    # write at (table[pos // page], pos % page); the
                    # gather below restores logical order, so the mask
                    # math is identical to the contiguous layout
                    widx = (jnp.take_along_axis(
                        page_tables, t // self.kv_page_size, axis=1),
                        t % self.kv_page_size)
                else:
                    widx = (jnp.arange(b)[:, None], t)

                def as_rows(c):
                    # cache → the logical view the attention consumes:
                    # a page gather when paged (covering only the
                    # tables the engine passed — its live-width slice,
                    # not max_len), identity otherwise
                    if paged:
                        return c[page_tables].reshape(
                            (b, page_tables.shape[1]
                             * self.kv_page_size) + c.shape[2:])
                    return c
                # every paged decode call is kernel-eligible: the
                # single-token step takes the step kernel, multi-token
                # windows (chunked prefill, speculative verify) take
                # the window kernel — unless the window escape hatch
                # drops them back onto the gather (step-only mode)
                use_kernel = (
                    paged and resolve_paged_kernel(self.paged_kernel)
                    and (s == 1 or
                         resolve_paged_window_kernel(self.paged_kernel)))
                if self.kv_int8:
                    def q8(u):
                        scale = jnp.maximum(
                            jnp.max(jnp.abs(u.astype(jnp.float32)), -1),
                            1e-8) / 127.0
                        qv = jnp.clip(jnp.round(
                            u.astype(jnp.float32) / scale[..., None]),
                            -127, 127).astype(jnp.int8)
                        return qv, scale

                    qk_, sk_ = q8(k)
                    qv_, sv_ = q8(v)
                    writes = [(ck, qk_), (cv, qv_), (sk, sk_),
                              (sv, sv_)]
                else:
                    writes = [(ck, k), (cv, v)]
                # EVERY cache write — paged or contiguous, kernel or
                # gather — goes through the partitioner shield (a
                # no-op on real TPU and single-device CPU): under a
                # multi-device interpret mesh the inline set-scatter
                # is re-lowered so cache replicas diverge and
                # reconcile additively, storing K exactly DOUBLED
                # (see ops/paged_attention.kv_cache_write)
                for var, val in writes:
                    var.value = kv_cache_write(
                        var.value, widx[0], widx[1], val)
                if use_kernel:
                    # walk the block table directly: partial softmax
                    # per pool page, LSE-merged, int8 dequant fused
                    # into the page load, dead pages skipped — per-call
                    # HBM traffic scales with live tokens
                    scales = ({"k_scale": sk.value, "v_scale": sv.value}
                              if self.kv_int8 else {})
                    sm = 1.0 / float(np.sqrt(dh))
                    if s == 1:  # generation hot loop — unchanged
                        o = paged_decode_attention(
                            q[:, 0], ck.value, cv.value, page_tables,
                            t[:, 0], sm_scale=sm, **scales)[:, None]
                    else:
                        # window positions are nondecreasing per row
                        # by construction of the engine's prefill and
                        # verify windows (idle/overhang rows repeat
                        # the last real entry) — the kernel's contract
                        o = paged_window_attention(
                            q, ck.value, cv.value, page_tables, t,
                            sm_scale=sm, **scales)
                elif self.kv_int8:
                    # multiply in f32 and cast the PRODUCT: casting the
                    # scales to bf16 first would throw away the very
                    # precision their f32 storage pays for (XLA fuses
                    # this into the attention einsum either way)
                    deq_k = (as_rows(ck.value).astype(jnp.float32)
                             * as_rows(sk.value)[..., None]).astype(
                                 x.dtype)
                    deq_v = (as_rows(cv.value).astype(jnp.float32)
                             * as_rows(sv.value)[..., None]).astype(
                                 x.dtype)
                    o = _masked_decode_attention(
                        q, jnp.repeat(deq_k, rep, axis=2),
                        jnp.repeat(deq_v, rep, axis=2), t, dh, x.dtype)
                else:
                    o = _masked_decode_attention(
                        q, jnp.repeat(as_rows(ck.value), rep, axis=2),
                        jnp.repeat(as_rows(cv.value), rep, axis=2),
                        t, dh, x.dtype)
        else:
            if self.seq_axis is not None:
                qt = q.transpose(0, 2, 1, 3)
                # per-TP-shard head count decides the strategy: each
                # model shard owns n_heads/tp whole heads (Megatron),
                # and the sp swap happens within that group
                tp = (self.seq_mesh.shape[self.head_axis]
                      if self.head_axis is not None else 1)
                if (self.n_heads // tp) % \
                        self.seq_mesh.shape[self.seq_axis]:
                    # heads don't split over the axis: rotate K/V blocks
                    # around the ring instead of swapping heads<->seq.
                    # The ring is GQA-aware: pass the UN-repeated
                    # n_kv_heads K/V so each hop moves only the real
                    # bytes (repeat happens per resident block inside)
                    from rafiki_tpu.ops.ring_attention import \
                        ring_attention

                    o = ring_attention(qt, k.transpose(0, 2, 1, 3),
                                       v.transpose(0, 2, 1, 3),
                                       self.seq_mesh, self.seq_axis,
                                       causal=True,
                                       batch_axis=DATA_AXIS,
                                       head_axis=self.head_axis)
                else:
                    from rafiki_tpu.ops.ulysses import ulysses_attention

                    # GQA-aware: un-repeated K/V — ulysses all-to-alls
                    # the small tensors when kv heads also divide the
                    # axis, and repeats before the swap otherwise
                    o = ulysses_attention(
                        qt, k.transpose(0, 2, 1, 3),
                        v.transpose(0, 2, 1, 3),
                        self.seq_mesh, self.seq_axis, causal=True,
                        batch_axis=DATA_AXIS,
                        head_axis=self.head_axis)
            else:
                o = flash_attention(
                    q.transpose(0, 2, 1, 3),
                    jnp.repeat(k, rep, axis=2).transpose(0, 2, 1, 3),
                    jnp.repeat(v, rep, axis=2).transpose(0, 2, 1, 3),
                    causal=True, kv_lens=lens)
            o = o.transpose(0, 2, 1, 3)
        o = o.reshape(b, s, self.n_heads * dh)
        return dense(d, name="wo")(o, adapter_ids)


class _DecoderBlock(nn.Module):
    n_heads: int
    n_kv_heads: int
    mlp_dim: int
    max_len: int
    lora_rank: int
    n_experts: int = 0  # >0 → MoE FFN (expert-parallel, ops/moe.py)
    moe_top_k: int = 1  # experts per token (1 Switch, 2 Mixtral-style)
    quantized: bool = False  # int8 base kernels (MoE experts stay f32)
    n_adapters: int = 0  # >0 → per-row stacked adapters (serving)
    seq_mesh: Any = None  # sequence parallelism (see _DecoderAttention)
    seq_axis: Optional[str] = None
    head_axis: Optional[str] = None  # sp×tp (see _DecoderAttention)
    rope_theta: float = 10000.0
    rope_scaling: Optional[Tuple[float, float, float, float]] = None
    kv_int8: bool = False  # serving-only int8 KV cache
    kv_page_size: int = 0  # >0 → paged KV pool (see _DecoderAttention)
    kv_pages: int = 0
    paged_kernel: Optional[bool] = None  # paged decode dispatch (ditto)

    @nn.compact
    def __call__(self, x, lens, positions, decode, adapter_ids=None,
                 page_tables=None):
        x = x + _DecoderAttention(
            self.n_heads, self.n_kv_heads, self.max_len, self.lora_rank,
            quantized=self.quantized, n_adapters=self.n_adapters,
            seq_mesh=self.seq_mesh, seq_axis=self.seq_axis,
            head_axis=self.head_axis,
            rope_theta=self.rope_theta, rope_scaling=self.rope_scaling,
            kv_int8=self.kv_int8, kv_page_size=self.kv_page_size,
            kv_pages=self.kv_pages, paged_kernel=self.paged_kernel,
            name="attn")(RMSNorm()(x), lens, positions, decode,
                         adapter_ids, page_tables)
        y = RMSNorm()(x)
        if self.n_experts > 0:
            from rafiki_tpu.ops.moe import MoEFeedForward

            return x + MoEFeedForward(self.n_experts, self.mlp_dim,
                                      router_top_k=self.moe_top_k,
                                      name="moe")(y)
        dense = functools.partial(LoRADense, rank=self.lora_rank,
                                  quantized=self.quantized,
                                  n_adapters=self.n_adapters)
        gate = dense(self.mlp_dim, name="gate")(y, adapter_ids)
        up = dense(self.mlp_dim, name="up")(y, adapter_ids)
        y = nn.silu(gate) * up  # SwiGLU
        return x + dense(x.shape[-1], name="down")(y, adapter_ids)


class Llama(nn.Module):
    """Decoder-only LM. Llama-3-8B = hidden 4096, depth 32, heads 32,
    kv_heads 8, mlp_dim 14336, vocab 128256."""

    vocab_size: int
    max_len: int
    hidden_dim: int = 4096
    depth: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    lora_rank: int = 0
    # compute dtype for activations/matmuls (params stay f32). None =
    # f32 compute; templates pass bf16 on TPU (f32 matmuls lower to
    # ~3x-cost multi-pass bf16 on the MXU).
    dtype: Any = None
    # gradient checkpointing per decoder block (train path only — the
    # decode path carries a mutable cache and recomputation would
    # double-write it): ~1/3 more FLOPs for O(depth) less activation
    # HBM. Identical math.
    remat: bool = False
    # three-way checkpointing schedule, superseding the legacy `remat`
    # bool when set: "none" (save everything), "full" (save only block
    # boundaries — max recompute, min HBM), "policy" (dots_saveable:
    # matmul outputs stay resident, elementwise ops recompute — the
    # middle ground). "" defers to `remat`. Identical math in all
    # three; only the HBM/recompute trade moves, which is why the knob
    # is searchable and feeds admission control.
    remat_policy: str = ""
    # >0 replaces every block's dense FFN with a top-k-routed MoE of
    # this many experts (ops/moe.py); expert weights shard over the
    # mesh's `model` axis (expert parallelism). The train step picks up
    # the load-balancing aux via mutable=["losses"].
    n_experts: int = 0
    # experts per token when n_experts > 0 (1 Switch, 2 Mixtral-style)
    moe_top_k: int = 1
    # serving-only int8 weight quantization of the LoRADense base
    # kernels (see LoRADense.quantized / quantize_llama_params)
    quantized: bool = False
    # >0 — multi-adapter serving: every LoRA site carries N stacked
    # adapters and each batch row applies the one named by the
    # ``adapter_ids`` call operand (see LoRADense.n_adapters). Build
    # the stacked params with :func:`stack_lora_adapters`.
    n_adapters: int = 0
    # sequence parallelism (train path): with seq_axis set, the causal
    # attention runs via ulysses all-to-alls over mesh[seq_axis] and
    # callers shard every (B, L) operand's L on that axis — long
    # sequences whose activations exceed one device's HBM train with
    # each device holding L/P of every activation. Static module
    # config, like dtype/remat (Mesh is hashable).
    seq_mesh: Any = None
    seq_axis: Optional[str] = None
    # sp×tp composition: mesh axis the head dim is tensor-parallel
    # sharded over — the sp collectives then run within each TP head
    # group (needs n_heads/tp % sp == 0 for ulysses; ring otherwise)
    head_axis: Optional[str] = None
    # RoPE base frequency: 10000 is the Llama-1/2 default; Llama-3
    # checkpoints use 500000 — a mismatched theta loads cleanly but
    # generates garbage, so the template threads the knob through
    rope_theta: float = 10000.0
    # Llama-3.1-style frequency-dependent context scaling as a STATIC
    # tuple (factor, low_freq_factor, high_freq_factor,
    # original_max_position_embeddings); None = unscaled (hashable —
    # dicts can't be flax module fields)
    rope_scaling: Optional[Tuple[float, float, float, float]] = None
    # serving-only int8 KV cache (decode path; see _DecoderAttention.
    # kv_int8): half the decode cache's HBM at bf16, bounded
    # quantization error. Training/eval never touch the decode branch.
    kv_int8: bool = False
    # >0 — paged KV cache (serving decode path; see _DecoderAttention.
    # kv_page_size): per layer K/V live in a (kv_pages, kv_page_size,
    # …) pool and each batch row maps logical→pool pages via the
    # ``page_tables`` call operand, so decode-cache HBM scales with the
    # pool (live tokens), not max_slots × max_len. kv_pages sizes the
    # pool (page 0 is the engine's scratch page). Training/eval and the
    # plain generate paths use contiguous-cache modules.
    kv_page_size: int = 0
    kv_pages: int = 0
    # paged decode dispatch (see _DecoderAttention.paged_kernel): None
    # (auto) = Pallas block-table kernel on TPU, page gather off-TPU;
    # True/False force one path. Serving-surface flag like kv_pages.
    paged_kernel: Optional[bool] = None

    @nn.compact
    def __call__(self, ids: jnp.ndarray, lens: Optional[jnp.ndarray] = None,
                 positions: Optional[jnp.ndarray] = None,
                 decode: bool = False,
                 return_hidden: bool = False,
                 adapter_ids: Optional[jnp.ndarray] = None,
                 page_tables: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        b, s = ids.shape
        if self.kv_page_size > 0:
            if self.max_len % self.kv_page_size:
                raise ValueError(
                    f"kv_page_size {self.kv_page_size} must divide "
                    f"max_len {self.max_len}")
            if self.kv_pages < 2:
                raise ValueError(
                    "kv_page_size > 0 needs kv_pages >= 2 (page 0 is "
                    "the scratch page; at least one usable page)")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        if lens is None:
            lens = jnp.full((b,), s, jnp.int32)
        x = nn.Embed(self.vocab_size, self.hidden_dim,
                     name="tok_embed")(ids)
        if self.dtype is not None:
            x = x.astype(self.dtype)
        block_cls = _DecoderBlock
        ckpt = self.remat_policy or ("full" if self.remat else "none")
        if ckpt not in ("none", "full", "policy"):
            raise ValueError(f"unknown remat_policy {ckpt!r} "
                             "(none/full/policy)")
        if ckpt != "none" and not decode:
            # decode stays static under remat (python-level branch in
            # the attention), so mark it non-traced — flax passes the
            # module itself as arg 0, putting decode at index 4
            block_cls = nn.remat(
                _DecoderBlock, static_argnums=(4,),
                policy=(jax.checkpoint_policies.dots_saveable
                        if ckpt == "policy" else None))
        for i in range(self.depth):
            x = block_cls(self.n_heads, self.n_kv_heads, self.mlp_dim,
                          self.max_len, self.lora_rank,
                          n_experts=self.n_experts,
                          moe_top_k=self.moe_top_k,
                          quantized=self.quantized,
                          n_adapters=self.n_adapters,
                          seq_mesh=self.seq_mesh, seq_axis=self.seq_axis,
                          head_axis=self.head_axis,
                          rope_theta=self.rope_theta,
                          rope_scaling=self.rope_scaling,
                          kv_int8=self.kv_int8,
                          kv_page_size=self.kv_page_size,
                          kv_pages=self.kv_pages,
                          paged_kernel=self.paged_kernel,
                          name=f"block_{i}")(x, lens, positions, decode,
                                             adapter_ids, page_tables)
        x = RMSNorm(name="final_norm")(x)
        if return_hidden:
            # chunked-loss path (chunked_lm_loss_terms): hand back the
            # final-norm activations so the caller can stream the
            # lm_head projection chunk-by-chunk instead of ever holding
            # (B, L, vocab) logits. lm_head params still initialize via
            # the default trace.
            return x
        return LoRADense(self.vocab_size, 0, quantized=self.quantized,
                         name="lm_head")(x)


def lm_valid_mask(seq_len: int, lens: jnp.ndarray,
                  example_mask: Optional[jnp.ndarray] = None
                  ) -> jnp.ndarray:
    """(B, L) bool: positions whose next-token loss counts — before
    each example's last real token, in unmasked examples. THE masking
    rule: the loss terms, the chunked loss, and gradient accumulation's
    global denominator must all agree on it."""
    pos = jnp.arange(seq_len)[None, :]
    valid = pos < (lens[:, None] - 1)
    if example_mask is not None:
        valid = valid & (example_mask[:, None] > 0)
    return valid


def lm_loss_terms(logits: jnp.ndarray, ids: jnp.ndarray,
                  lens: jnp.ndarray,
                  example_mask: Optional[jnp.ndarray] = None
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masked next-token cross-entropy: (sum of losses, valid count).

    Targets are ``ids`` shifted left; positions at/after each example's
    last real token (and examples with ``example_mask == 0``) are
    excluded. One implementation shared by train/evaluate/dry-run.
    """
    targets = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
    valid = lm_valid_mask(ids.shape[1], lens, example_mask)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets)
    return jnp.sum(losses * valid), jnp.sum(valid)


def chunked_lm_loss_terms(hidden: jnp.ndarray, head_kernel: jnp.ndarray,
                          ids: jnp.ndarray, lens: jnp.ndarray,
                          example_mask: Optional[jnp.ndarray] = None,
                          chunk: int = 256
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``lm_loss_terms`` without ever materializing (B, L, vocab) logits.

    The full-logits tensor is the largest activation in LM training by
    far — Llama-3's 128k vocab at (8, 2048) is ~16 GB in f32, several
    times the model's entire activation footprint. This streams the
    lm_head projection over sequence chunks with ``lax.scan``: each step
    projects one (B, chunk, D) slice of the final-norm activations,
    reduces straight to summed cross-entropy, and discards the chunk's
    logits. ``jax.checkpoint`` on the chunk body keeps the BACKWARD pass
    at one chunk of logits too (recomputed per step), so peak logits
    memory drops from O(L·V) to O(chunk·V) in both passes.

    Same math as ``lm_loss_terms`` up to f32 summation order (the scan
    folds per-chunk partial sums sequentially, so low bits differ from
    the dense path's single reduction): the projection runs in
    ``hidden.dtype`` (matching ``LoRADense``) and the softmax in f32.
    Sequence pads introduced to reach a chunk multiple are masked out of
    both the sum and the count.
    """
    targets = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
    valid = lm_valid_mask(hidden.shape[1], lens, example_mask)
    return (_chunked_ce_sum(hidden, targets, valid, head_kernel, chunk),
            jnp.sum(valid))


def _chunked_ce_sum(hidden: jnp.ndarray, targets: jnp.ndarray,
                    valid: jnp.ndarray, head_kernel: jnp.ndarray,
                    chunk: int, unroll: bool = False) -> jnp.ndarray:
    """The chunked projection+CE scan over precomputed targets/valid —
    shared by the dense-path wrapper above and the sequence-parallel
    variant below (which shards the SEQUENCE and must therefore shift
    targets globally before partitioning).

    ``unroll`` replaces the ``lax.scan`` with a Python loop over the
    (static) chunk count: required when this runs INSIDE a ``shard_map``
    — transposing a scan through shard_map mis-specs the scalar carry
    on older jax (0.4.x), and the sp variant differentiates through
    exactly that composition. Same math, unrolled HLO."""
    b, length, d = hidden.shape
    chunk = max(1, min(int(chunk), length))
    pad = (-length) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    n_chunks = (length + pad) // chunk
    # scan carries the running sum; xs walk the chunk axis
    hs = hidden.reshape(b, n_chunks, chunk, d).transpose(1, 0, 2, 3)
    ts = targets.reshape(b, n_chunks, chunk).transpose(1, 0, 2)
    vs = valid.reshape(b, n_chunks, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def _chunk_sum(h, t, v):
        logits = h @ head_kernel.astype(h.dtype)  # (B, chunk, V) — local
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), t)
        return jnp.sum(losses * v)

    if unroll:
        total = jnp.zeros((), jnp.float32)
        for i in range(n_chunks):
            total = total + _chunk_sum(hs[i], ts[i], vs[i])
        return total

    def body(total, xs):
        h, t, v = xs
        return total + _chunk_sum(h, t, v), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                            (hs, ts, vs))
    return total


def chunked_lm_loss_terms_sp(hidden: jnp.ndarray,
                             head_kernel: jnp.ndarray,
                             ids: jnp.ndarray, lens: jnp.ndarray,
                             example_mask: Optional[jnp.ndarray],
                             chunk: int, mesh, data_axis: str,
                             sp_axis: str
                             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`chunked_lm_loss_terms` with the SEQUENCE dim sharded over
    ``mesh[sp_axis]`` (the long-context train path) — previously the
    two knobs were mutually exclusive because chunk slicing through
    GSPMD would re-gather the sp-sharded activations every chunk.

    The composition that avoids all gathers: the next-token SHIFT runs
    globally first (targets/valid are (B, L) int/bool — trivial bytes —
    and the shift is what crosses shard boundaries), then a
    ``shard_map`` over (data, sp) hands each device its LOCAL
    (B/dp, L/sp) slice of hidden/targets/valid; every device streams
    its own chunks through the shared scan and the (sum, count) reduce
    with one scalar ``psum``. The head kernel stays replicated (this
    variant is for the dp×sp regime; sp×tp keeps the dense loss —
    a vocab-sharded head inside the shard would need cross-axis
    softmax reductions). Same math as the dense path up to f32
    summation order."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rafiki_tpu.ops.common import shard_map_checked

    targets = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)))
    valid = lm_valid_mask(hidden.shape[1], lens, example_mask)
    sp = mesh.shape[sp_axis]
    if hidden.shape[1] % sp:
        raise ValueError(f"sequence {hidden.shape[1]} must divide the "
                         f"sp axis ({sp}) for the sharded chunked loss")
    chunk = max(1, min(int(chunk), hidden.shape[1] // sp))

    h_spec = P(data_axis, sp_axis, None)
    t_spec = P(data_axis, sp_axis)

    @functools.partial(
        shard_map_checked, mesh=mesh,
        in_specs=(h_spec, P(None, None), t_spec, t_spec),
        out_specs=(P(), P()))
    def _local(h_l, kernel, t_l, v_l):
        total = _chunked_ce_sum(h_l, t_l, v_l, kernel, chunk,
                                unroll=True)
        count = jnp.sum(v_l)
        return (jax.lax.psum(total, (data_axis, sp_axis)),
                jax.lax.psum(count, (data_axis, sp_axis)))

    hidden = jax.device_put(hidden, NamedSharding(mesh, h_spec))
    return _local(hidden, head_kernel, targets,
                  valid.astype(jnp.float32))


def quantize_llama_params(params: Any) -> Any:
    """f32 param tree → the ``quantized=True`` module's tree: every
    LoRADense base ``kernel`` becomes int8 ``qkernel`` + per-output-
    channel f32 ``qscale`` (symmetric absmax: scale = max|col| / 127);
    adapters, norms, embeddings, and MoE experts pass through unchanged.

    Weight-only post-training quantization for SERVING: persistent
    weight HBM drops 4x and the bandwidth-bound decode loop reads a
    quarter of the bytes. Most kernels are LoRA-frozen bases whose
    trained signal lives in the untouched f32 adapters; the trained
    ``lm_head`` kernel is quantized too (standard W8 PTQ — its
    per-element error is bounded like the rest). Reconstruction error
    is bounded by scale/2 per element (≤ ~0.4% of each channel's
    absmax); training and evaluate() keep the f32 originals.
    """
    def walk(tree: Any) -> Any:
        if not isinstance(tree, dict):
            return tree
        out = {}
        for name, sub in tree.items():
            if (isinstance(sub, dict) and "kernel" in sub
                    and getattr(sub["kernel"], "ndim", 0) == 2):
                k = jnp.asarray(sub["kernel"], jnp.float32)
                scale = jnp.maximum(jnp.max(jnp.abs(k), axis=0), 1e-8) / 127.0
                q = jnp.clip(jnp.round(k / scale[None, :]),
                             -127, 127).astype(jnp.int8)
                out[name] = {"qkernel": q, "qscale": scale,
                             **{kk: vv for kk, vv in sub.items()
                                if kk != "kernel"}}
            else:
                out[name] = walk(sub)
        return out

    return walk(params)


def stack_block_params(params: Any, depth: int, n_stages: int) -> Any:
    """Canonical ``block_i`` params → (S, k, …) pipeline stacks (stage
    s owns layers [s·k, (s+1)·k), k = depth/S)."""
    from rafiki_tpu.parallel.pipeline import stack_stage_params

    k = depth // n_stages
    blocks = [params[f"block_{i}"] for i in range(depth)]
    # one stacking convention everywhere: layers within a stage AND
    # stages themselves stack via the same helper
    stages = [stack_stage_params(blocks[s * k:(s + 1) * k])
              for s in range(n_stages)]
    return stack_stage_params(stages)


def pipelined_lm_forward(module: Llama, params: Any, ids: jnp.ndarray,
                         lens: jnp.ndarray, mesh, n_micro: int,
                         remat: bool = False,
                         batch_axis: Optional[str] = None) -> jnp.ndarray:
    """``module.apply({"params": params}, ids, lens=lens)`` with the
    decoder blocks PIPELINED over the mesh's ``pipe`` axis.

    Identical math to the canonical forward (tested logits- and
    grads-equal): embedding and head run outside the pipe; the blocks
    restack to (S, k, …) and each stage scans its k layers; microbatches
    stream through ``parallel.pipeline.pipeline_apply`` carrying
    (hidden, lens, positions) as the activation pytree. Train-path only
    (no KV cache). MoE blocks are rejected — their aux loss cannot sow
    through the pipeline scan yet, and silently training without load
    balancing would be wrong.
    """
    from rafiki_tpu.parallel.pipeline import pipeline_apply

    if module.n_experts > 0:
        raise ValueError("pipelined training does not support MoE "
                         "blocks yet (aux loss cannot sow through the "
                         "pipeline scan)")
    n_stages = mesh.shape["pipe"]
    if module.depth % n_stages:
        raise ValueError(f"depth {module.depth} must be divisible by "
                         f"pipeline stages {n_stages}")
    b, s = ids.shape
    if b % n_micro:
        raise ValueError(f"batch {b} must be divisible by "
                         f"n_micro {n_micro}")
    x = nn.Embed(module.vocab_size, module.hidden_dim).apply(
        {"params": params["tok_embed"]}, ids)
    if module.dtype is not None:
        x = x.astype(module.dtype)
    pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    stacked = stack_block_params(params, module.depth, n_stages)
    mb = b // n_micro
    act = {"h": x.reshape(n_micro, mb, s, module.hidden_dim),
           "lens": lens.reshape(n_micro, mb),
           "pos": pos.reshape(n_micro, mb, s)}
    block = _DecoderBlock(module.n_heads, module.n_kv_heads,
                          module.mlp_dim, module.max_len,
                          module.lora_rank, n_experts=0)

    def stage_fn(p_stage, a):
        def layer(h, p_layer):
            return block.apply({"params": p_layer}, h, a["lens"],
                               a["pos"], False), None

        h, _ = jax.lax.scan(layer, a["h"], p_stage)
        return {"h": h, "lens": a["lens"], "pos": a["pos"]}

    out = pipeline_apply(stage_fn, stacked, act, mesh, axis="pipe",
                         batch_axis=batch_axis, remat=remat)
    h = out["h"].reshape(b, s, module.hidden_dim)
    h = RMSNorm(name="final_norm").apply({"params": params["final_norm"]},
                                         h)
    return LoRADense(module.vocab_size, 0, name="lm_head").apply(
        {"params": params["lm_head"]}, h)


def _kp_path(kp) -> str:
    """Render a tree_map_with_path key path as a lowercase '/'-joined
    string. lower(): flax auto-names unnamed instances "RMSNorm_0"
    etc."""
    return "/".join(str(getattr(k, "key", k)) for k in kp).lower()


def lora_trainable_mask(params: Any) -> Any:
    """True for LoRA adapters, norms, the LM head, and MoE layers;
    False (frozen) for base kernels and the embedding — the LoRA
    fine-tuning recipe. MoE routers/experts have no pretrained base (no
    HF Llama checkpoint carries them — convert.py leaves them at init),
    so freezing them would inject a random frozen transform into every
    residual stream; they always train."""

    def trainable(kp, _) -> bool:
        path = _kp_path(kp)
        return ("lora_" in path or "norm" in path or "/moe/" in path
                or path.startswith("lm_head"))

    return jax.tree_util.tree_map_with_path(trainable, params)


def adapter_only_mask(params: Any) -> Any:
    """True ONLY for ``lora_a``/``lora_b`` leaves — the strict LoRA
    recipe (norms, lm_head, embeddings all frozen). Trials trained
    under this mask differ exclusively in their adapters, which is the
    contract :func:`stack_lora_adapters` / multi-adapter serving
    enforces."""

    def trainable(kp, _) -> bool:
        path = _kp_path(kp)
        return "lora_a" in path or "lora_b" in path

    return jax.tree_util.tree_map_with_path(trainable, params)


def estimate_train_device_bytes(module: "Llama", *,
                                batch_size: int,
                                data_parallel: int = 1,
                                model_parallel: int = 1,
                                sequence_parallel: int = 1,
                                grad_accum: int = 1,
                                loss_chunk: int = 0,
                                remat: bool = True,
                                remat_policy: str = "",
                                adapters_only: bool = False,
                                pipeline_stages: int = 1,
                                pipeline_microbatches: int = 0,
                                fsdp_min_size: int = 2 ** 12,
                                overlap_collectives: bool = False
                                ) -> Dict[str, int]:
    """Per-device HBM budget for one train step, from real shape math.

    The admission-control formula (SURVEY §2.2's v5e-16 stretch config
    needs proof the 8B LoRA job FITS a 16GB chip before a worker
    claims it — an OOM mid-trial wastes the whole slot):

    - ``params`` / ``grads`` / ``opt`` are EXACT: the abstract param
      tree (``jax.eval_shape`` of the real init — no allocation), the
      template's ACTUAL sharding rules (``param_shardings`` with
      ``TP_RULES`` + fsdp over an :class:`~jax.sharding.AbstractMesh`,
      so a 16-chip budget computes on any host), and per-leaf
      ``shard_shape`` byte counts. Grads are f32 and param-sharded
      (``value_and_grad`` materializes the full tree; the frozen-leaf
      mask applies at ``tx.update``, after the tree exists — and with
      ``grad_accum>1`` the scan carries a second, accumulator copy).
      Opt state is adamw mu+nu over TRAINABLE leaves only
      (``optax.multi_transform`` + ``set_to_zero`` allocates nothing
      for frozen leaves).
    - ``activations`` is a documented UPPER BOUND (XLA frees/fuses
      more than this): with remat, block-boundary residuals
      (depth x tokens_dev x hidden) live through the backward, plus
      one block's recompute working set — per token roughly
      q,k,v,attn-out (~4 x hidden) + SwiGLU gate/up/down
      (~3 x mlp_dim) doubled for their cotangents — plus the logits
      chunk (f32 logits + cotangent, vocab tp-sharded; ``loss_chunk=0``
      means full-sequence logits, the large-vocab danger case).
      Without remat the working set multiplies by depth instead.
    - ``transient``: the largest single weight's compute-dtype cast
      (bf16 matmul operands are materialized per layer then freed).

    tokens_dev = batch/(dp·grad_accum) x max_len/sp on each device;
    dims follow the 3-axis (data, sp, model) train mesh exactly as
    :meth:`LlamaLoRA.train` builds it. Returns a dict of byte counts
    plus ``total``.
    """
    from jax.sharding import AbstractMesh, NamedSharding

    from rafiki_tpu.parallel.sharding import (DATA_AXIS, MODEL_AXIS,
                                              param_shardings)

    def abstract_mesh(sizes, names):
        # jax moved AbstractMesh from shape_tuple=((name, size), ...)
        # to (axis_sizes, axis_names) positional args; construct
        # whichever this jax speaks (the old form raises TypeError
        # inside __init__ when handed the new argument layout)
        try:
            return AbstractMesh(tuple(sizes), tuple(names))
        except TypeError:
            return AbstractMesh(tuple(zip(names, sizes)))

    dp, tp, sp = data_parallel, model_parallel, sequence_parallel
    if pipeline_stages > 1:
        return _estimate_pipeline_device_bytes(
            module, batch_size=batch_size, data_parallel=dp,
            pipeline_stages=pipeline_stages,
            pipeline_microbatches=pipeline_microbatches,
            adapters_only=adapters_only)
    if sp > 1 and tp > 1:
        mesh = abstract_mesh((dp, sp, tp), (DATA_AXIS, "sp", MODEL_AXIS))
    elif sp > 1:
        mesh = abstract_mesh((dp, sp), (DATA_AXIS, "sp"))
    else:
        mesh = abstract_mesh((dp, tp), (DATA_AXIS, MODEL_AXIS))
    tp_rules = None if (sp > 1 and tp == 1) else TP_RULES

    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, module.max_len),
                                      jnp.int32)))["params"]
    shardings = param_shardings(abstract, mesh, tp_rules=tp_rules,
                                fsdp=True, min_size=fsdp_min_size)

    def leaf_dev_bytes(leaf, sh: NamedSharding) -> int:
        return int(np.prod(sh.shard_shape(leaf.shape))) * \
            np.dtype(leaf.dtype).itemsize

    flat_p = jax.tree_util.tree_leaves(abstract)
    flat_s = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    params_dev = sum(leaf_dev_bytes(l, s) for l, s in zip(flat_p, flat_s))
    # grads: full f32 tree, param shardings; accumulation carries a
    # second copy through the scan
    grads_dev = sum(
        int(np.prod(s.shard_shape(l.shape))) * 4
        for l, s in zip(flat_p, flat_s)) * (2 if grad_accum > 1 else 1)
    # opt: adamw mu+nu for trainable leaves (f32, param-sharded)
    mask = (adapter_only_mask if adapters_only
            else lora_trainable_mask)(abstract)
    flat_m = jax.tree_util.tree_leaves(mask)
    opt_dev = 2 * sum(int(np.prod(s.shard_shape(l.shape))) * 4
                      for l, s, m in zip(flat_p, flat_s, flat_m) if m)

    act_bytes = 2 if module.dtype == jnp.bfloat16 else 4
    tokens_dev = max(1, batch_size // (dp * max(1, grad_accum))) * \
        max(1, module.max_len // sp)
    h, mlp = module.hidden_dim, module.mlp_dim
    per_block = tokens_dev * (4 * h + 3 * mlp) * act_bytes * 2  # +cotan
    acts_dev = _remat_activation_bytes(
        remat_policy or ("full" if remat else "none"),
        module.depth, tokens_dev, h, mlp, act_bytes, per_block)
    chunk = loss_chunk or module.max_len // sp
    logits_rows = max(1, batch_size // (dp * max(1, grad_accum)))
    logits_dev = logits_rows * chunk * \
        -(-module.vocab_size // (tp if tp_rules else 1)) * 4 * 2
    transient = max(
        (int(np.prod(s.shard_shape(l.shape))) for l, s in
         zip(flat_p, flat_s)), default=0) * act_bytes
    if overlap_collectives:
        # async fsdp all-gathers double-buffer: layer k+1's gathered
        # weights materialize while layer k computes, so one more
        # gathered-weight copy is live at the peak
        transient *= 2

    out = {"params": params_dev, "grads": grads_dev, "opt": opt_dev,
           "activations": acts_dev + logits_dev, "transient": transient}
    out["total"] = sum(out.values())
    return out


def _remat_activation_bytes(policy: str, depth: int, tokens: int,
                            h: int, mlp: int, act_bytes: int,
                            per_block: int) -> int:
    """Activation bytes resident through the backward under each
    checkpointing schedule — the admission lever the ``remat_policy``
    knob moves (ordered none > policy > full at any shape):

    - ``none``: every block's working set survives to the backward.
    - ``policy`` (dots_saveable): each block's matmul OUTPUTS (~4·h
      attention + ~3·mlp SwiGLU per token) stay resident; elementwise
      ops recompute, and so do the cotangent temporaries (hence no ×2).
    - ``full``: only block-boundary residuals (h per token per block)
      survive, plus one block's recompute working set.
    """
    if policy == "none":
        return depth * per_block
    if policy == "policy":
        return depth * tokens * (4 * h + 3 * mlp) * act_bytes + per_block
    return depth * tokens * h * act_bytes + per_block


def estimate_gang_device_bytes(module: "Llama", *, batch_size: int,
                               gang_size: int, remat_policy: str = "",
                               adapters_only: bool = False,
                               overlap_collectives: bool = False
                               ) -> Dict[str, int]:
    """HBM budget for a K-lane gang train step (gang-compiled tuning).

    The gang executor runs ONE unsharded program: the frozen base tree
    is closed over (broadcast — one copy regardless of K, including its
    never-updated trainable-leaf slots), while the K lanes stack only
    TRAINABLE leaves plus their Adam state, and every per-token
    activation term multiplies by K. ``params``/``grads``/``opt`` are
    exact (the estimator-vs-measured test holds them to the real pool
    bytes); activations follow :func:`_remat_activation_bytes`, which is
    what lets admission admit at ``remat_policy=full`` a gang it refuses
    at ``none``.
    """
    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, module.max_len),
                                      jnp.int32)))["params"]
    flat_p = jax.tree_util.tree_leaves(abstract)
    base_bytes = sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                     for l in flat_p)
    mask = (adapter_only_mask if adapters_only
            else lora_trainable_mask)(abstract)
    train_bytes = sum(
        int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
        for l, m in zip(flat_p, jax.tree_util.tree_leaves(mask)) if m)
    k = max(1, int(gang_size))
    params_dev = base_bytes + k * train_bytes
    grads_dev = k * train_bytes  # grads exist for trainable leaves only
    opt_dev = 2 * k * train_bytes  # adam mu+nu per lane

    act_bytes = 2 if module.dtype == jnp.bfloat16 else 4
    tokens = batch_size * module.max_len
    h, mlp = module.hidden_dim, module.mlp_dim
    per_block = tokens * (4 * h + 3 * mlp) * act_bytes * 2
    acts = _remat_activation_bytes(remat_policy or "none", module.depth,
                                   tokens, h, mlp, act_bytes, per_block)
    logits = batch_size * module.max_len * module.vocab_size * 4 * 2
    transient = max((int(np.prod(l.shape)) for l in flat_p),
                    default=0) * act_bytes
    if overlap_collectives:
        transient *= 2
    out = {"params": params_dev, "grads": grads_dev, "opt": opt_dev,
           "activations": (acts + logits) * k, "transient": transient}
    out["total"] = sum(out.values())
    # informational (already inside params): the K-independent
    # broadcast-base share, so callers can separate one-copy cost from
    # per-lane cost
    out["base"] = base_bytes
    return out


def _estimate_pipeline_device_bytes(module: "Llama", *, batch_size: int,
                                    data_parallel: int,
                                    pipeline_stages: int,
                                    pipeline_microbatches: int,
                                    adapters_only: bool) -> Dict[str, int]:
    """Pipeline-mode budget: train() REPLICATES the param tree on every
    device of the pipe x data mesh (the rep_pp device_put — weight-
    sharded pipeline storage is future work), so params/grads/opt count
    UNSHARDED here; admission control must see the replicated reality,
    not the tp+fsdp layout pp mode doesn't use. Activations: GPipe
    holds every in-flight microbatch's block-boundary activations for
    this device's depth/pp stage through the backward, plus one
    microbatch's within-block working set and the last stage's logits."""
    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, module.max_len),
                                      jnp.int32)))["params"]
    flat_p = jax.tree_util.tree_leaves(abstract)
    params_dev = sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                     for l in flat_p)
    grads_dev = sum(int(np.prod(l.shape)) * 4 for l in flat_p)
    mask = (adapter_only_mask if adapters_only
            else lora_trainable_mask)(abstract)
    opt_dev = 2 * sum(
        int(np.prod(l.shape)) * 4 for l, m in
        zip(flat_p, jax.tree_util.tree_leaves(mask)) if m)

    act_bytes = 2 if module.dtype == jnp.bfloat16 else 4
    pp = pipeline_stages
    n_micro = pipeline_microbatches or pp
    dp = max(1, data_parallel)
    rows_dev = max(1, batch_size // dp)  # all microbatches' rows
    micro_rows = max(1, batch_size // (dp * n_micro))
    h, mlp = module.hidden_dim, module.mlp_dim
    stage_depth = max(1, module.depth // pp)
    acts_dev = (stage_depth * rows_dev * module.max_len * h * act_bytes
                + micro_rows * module.max_len * (4 * h + 3 * mlp)
                * act_bytes * 2)
    logits_dev = micro_rows * module.max_len * module.vocab_size * 4 * 2
    transient = max((int(np.prod(l.shape)) for l in flat_p),
                    default=0) * act_bytes
    out = {"params": params_dev, "grads": grads_dev, "opt": opt_dev,
           "activations": acts_dev + logits_dev, "transient": transient}
    out["total"] = sum(out.values())
    return out


def _default_kv_pages(max_slots: int, max_len: int,
                      page_size: int) -> int:
    """Pool size when the operator sets ``kv_page_size`` but not
    ``kv_pages``: one scratch page plus full coverage (every slot can
    reach max_len), i.e. paged mechanics with zero admission stalls and
    no footprint saving. Memory wins come from sizing ``kv_pages`` DOWN
    to the expected live-token load (docs/operations.md)."""
    return 1 + max_slots * (max_len // page_size)


def stack_lora_adapters(trees: List[Any], validate: bool = True) -> Any:
    """Merge N adapter-only fine-tunes of one base into a single
    multi-adapter param tree for ``Llama(n_adapters=N)``.

    ``lora_a``/``lora_b`` leaves are stacked along a new leading
    adapter axis; every other leaf is taken from ``trees[0]`` and (when
    ``validate``) checked byte-identical across inputs — a mismatch
    means the trials were NOT trained with ``adapters_only`` and
    cannot share one serving engine (their norms/lm_head diverged).
    ``validate=False`` skips the scan for huge trees whose provenance
    is already known."""
    if not trees:
        raise ValueError("need at least one adapter tree")

    def merge(kp, *leaves):
        path = _kp_path(kp)
        if "lora_a" in path or "lora_b" in path:
            return jnp.stack([jnp.asarray(lf) for lf in leaves], axis=0)
        if validate:
            first = np.asarray(leaves[0])
            for i, lf in enumerate(leaves[1:], start=1):
                if not np.array_equal(first, np.asarray(lf)):
                    raise ValueError(
                        f"non-adapter leaf {path!r} differs between "
                        f"adapter 0 and {i}: multi-adapter serving "
                        "requires trials trained with adapters_only=True "
                        "(shared base/norms/lm_head)")
        return leaves[0]

    return jax.tree_util.tree_map_with_path(merge, trees[0], *trees[1:])


@functools.partial(jax.jit, static_argnums=(0, 4))
def _greedy_generate_impl(module: Llama, params: Any, prompt: jnp.ndarray,
                          plens: jnp.ndarray, max_new: int) -> jnp.ndarray:
    b, p_len = prompt.shape
    total = p_len + max_new
    cache = module.init(jax.random.PRNGKey(0),
                        jnp.zeros((b, 1), jnp.int32), decode=True)["cache"]

    def step(carry, t):
        cache, tok = carry
        logits, muts = module.apply(
            {"params": params, "cache": cache}, tok[:, None], decode=True,
            positions=jnp.full((b, 1), t, jnp.int32), mutable=["cache"])
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1)
        nxt = nxt.astype(jnp.int32)
        # next input: the prompt token while it lasts, else our own output
        in_prompt = (t + 1) < plens
        tok_next = jnp.where(in_prompt,
                             prompt[:, jnp.minimum(t + 1, p_len - 1)], nxt)
        return (muts["cache"], tok_next), nxt

    (_, _), outs = jax.lax.scan(step, (cache, prompt[:, 0]),
                                jnp.arange(total - 1))
    # outs[t] is the model's prediction after consuming token t; example i's
    # generation starts at t = plens[i]-1
    outs = outs.transpose(1, 0)  # (b, total-1)
    gather = (plens[:, None] - 1) + jnp.arange(max_new)[None, :]
    gather = jnp.clip(gather, 0, total - 2)
    return jnp.take_along_axis(outs, gather, axis=1)


def greedy_generate(module: Llama, params: Any, prompt_ids: np.ndarray,
                    prompt_lens: np.ndarray, max_new: int) -> jnp.ndarray:
    """Greedy decode: scan one compiled cache step over prompt+generation.

    ``prompt_ids`` (b, P) left-aligned with PAD tails; each example starts
    generating right after its own last prompt token, so pads never enter
    the cache. Returns (b, max_new) generated ids.

    Compiled ONCE per (module config, batch, prompt width, max_new):
    ``module`` and ``max_new`` ride as static jit args, so repeated
    serving calls at bucketed shapes hit the executable cache instead of
    re-tracing the scan (the round-1/round-2 compile-per-request bug).
    """
    return _greedy_generate_impl(module, params,
                                 jnp.asarray(prompt_ids, jnp.int32),
                                 jnp.asarray(prompt_lens, jnp.int32),
                                 int(max_new))


class LlamaLoRA(BaseModel):
    """Causal-LM template: LoRA fine-tune over a 2-D (fsdp × tensor) mesh,
    greedy generation for serving. Accepts the ``.jsonl`` text corpus
    format (labels, if present, are ignored)."""

    TASKS = (TaskType.LANGUAGE_MODELING,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(6),
            "vocab_size": FixedKnob(1 << 14),
            "hidden_dim": CategoricalKnob([64, 128, 256, 512],
                                          shape_relevant=True),
            "depth": IntegerKnob(2, 8, shape_relevant=True),
            "n_heads": CategoricalKnob([4, 8], shape_relevant=True),
            "kv_ratio": CategoricalKnob([1, 2, 4], shape_relevant=True),
            "lora_rank": CategoricalKnob([4, 8, 16], shape_relevant=True),
            "max_len": CategoricalKnob([32, 64, 128], shape_relevant=True),
            "model_parallel": CategoricalKnob([1, 2, 4],
                                              shape_relevant=True),
            # traceable: rides the gang step as a traced per-lane
            # scalar — K learning rates share one compiled program
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True,
                                       traceable=True),
            # LoRA rank-scale (the α/r of the LoRA paper): the forward
            # applies scale·(x·A·B). Traceable like learning_rate —
            # per-lane in a gang — and FOLDED into lora_b at export, so
            # serving trees need no scale plumbing (scale=1 is the
            # legacy forward bit-for-bit)
            "lora_scale": FloatKnob(0.25, 4.0, is_exp=True,
                                    traceable=True),
            "batch_size": CategoricalKnob([8, 16, 32], shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            # gradient checkpointing (train path): bigger batches for
            # ~1/3 extra FLOPs when activations are HBM-bound
            "remat": FixedKnob(False),
            # searchable checkpointing SCHEDULE, superseding the legacy
            # `remat` bool when not "none": none / full / policy
            # (dots_saveable — matmul outputs resident, elementwise
            # recomputed). Static → each value is its own gang compile
            # bucket; feeds estimate_device_budget so admission can
            # trade HBM for recompute instead of refusing the job.
            "remat_policy": CategoricalKnob(["none", "full", "policy"]),
            # overlap the fsdp all-gather/reduce-scatter path with
            # compute (async collectives + latency-hiding scheduler,
            # parallel.sharding.overlap_compiler_options). TPU-only
            # compiler options — a no-op bucket split on CPU. Costs one
            # extra gathered-weight buffer at peak (estimator's
            # transient term).
            "overlap_collectives": CategoricalKnob([False, True]),
            # train ONLY the lora_a/lora_b leaves (norms/lm_head frozen
            # too): the contract multi-adapter serving needs — N trials
            # that differ ONLY in adapters can then share one engine
            # (make_multi_adapter_engine / stack_lora_adapters). A
            # policy, not a search dimension: defaults off, the
            # operator enables it per job via knob_overrides
            "adapters_only": PolicyKnob("ADAPTERS_ONLY"),
            # >1 shards the SEQUENCE dim of every train activation over
            # this many devices — the long-context train path:
            # ulysses all-to-alls when per-TP-shard heads divide it,
            # ring K/V rotation otherwise (both exact). Composes with
            # data parallelism AND tensor parallelism: model_parallel>1
            # builds a (data, sp, model) 3-axis mesh with the sp
            # collectives running within each TP head group (needs
            # n_heads and kv heads divisible by model_parallel).
            # Composes with loss_chunk at model_parallel=1 (each shard
            # streams its own loss chunks — chunked_lm_loss_terms_sp).
            # max_len must divide by it; mutually exclusive with
            # pipeline_stages>1, MoE, and loss_chunk+model_parallel>1.
            "sequence_parallel": FixedKnob(1),
            # >1 pipelines the decoder blocks over this many devices
            # (GPipe microbatching, parallel/pipeline.py); depth must
            # divide by it; mutually exclusive with model_parallel>1.
            # Train path only — serving is unchanged. NOTE: pp mode
            # currently keeps params REPLICATED per device (right when
            # ACTIVATIONS, not weights, are the memory bound; weight-
            # sharded pipeline storage is future work).
            "pipeline_stages": FixedKnob(1),
            # microbatches per batch in pipeline mode (0 → one per
            # stage). GPipe's bubble fraction is (S-1)/(M+S-1): raise M
            # well above pipeline_stages to amortize it.
            "pipeline_microbatches": FixedKnob(0),
            # >0 → stream the lm_head projection + cross-entropy over
            # sequence chunks of this size in the train step instead of
            # materializing (B, L, vocab) logits — the dominant
            # activation at large vocab (chunked_lm_loss_terms). 0 keeps
            # the dense loss. Identical math either way.
            "loss_chunk": FixedKnob(0),
            # >0 → MoE FFN with this many experts per block (expert
            # parallelism over the mesh's model axis; ops/moe.py)
            "moe_experts": FixedKnob(0),
            # experts per token (1 Switch, 2 Mixtral-style)
            "moe_top_k": FixedKnob(1),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
            # serve with int8 weight-only-quantized base kernels
            # (quantize_llama_params): 4x less weight HBM for the
            # bandwidth-bound decode loop. predict()/make_decode_engine
            # only — training and evaluate() (the tuning objective)
            # stay full precision.
            "quantize_int8": FixedKnob(False),
            # >1 accumulates gradients over this many micro-batches
            # before each optimizer step (lax.scan) — big-batch math
            # exactly, one micro-batch's activations in HBM at a time.
            # Mutually exclusive with pipeline_stages>1 (GPipe already
            # microbatches); batch_size rounds to a multiple.
            "grad_accum": FixedKnob(1),
            # serving-only int8 KV cache: halves decode-cache HBM at
            # bf16 (more slots / longer contexts per chip) for a
            # bounded per-vector quantization error; generations are
            # no longer bit-identical to the f32-cache engine
            "kv_cache_int8": FixedKnob(False),
            # RoPE base frequency; match the pretrained checkpoint
            # (Llama-1/2: 10000, Llama-3: 500000). A wrong theta loads
            # cleanly but generates garbage.
            "rope_theta": FixedKnob(10000.0),
            # Llama-3.1-style frequency-dependent context scaling: a
            # JSON object string (or dict at construction) with
            # factor / low_freq_factor / high_freq_factor /
            # original_max_position_embeddings; "" = unscaled. Match
            # the checkpoint's config.json rope_scaling.
            "rope_scaling": FixedKnob(""),
            # serving-quality runs: a trained byte-BPE artifact
            # (data/bpe.py) replaces the hash tokenizer, and an
            # HF-convention safetensors checkpoint (models/convert.py)
            # replaces random base weights. Empty = round-3 behavior.
            "tokenizer_path": FixedKnob(""),
            "pretrained_path": FixedKnob(""),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._qparams: Optional[Any] = None  # lazy int8 serving tree
        self._id2tok: Dict[int, str] = {}
        self._fwd: Optional[Any] = None
        tok_path = str(self.knobs.get("tokenizer_path") or "")
        if tok_path:
            from rafiki_tpu.data.bpe import ByteBPETokenizer

            # vocab_size follows the artifact — the embedding must match
            # the merge table, not the knob default
            self.tokenizer: Any = ByteBPETokenizer.load(tok_path)
        else:
            self.tokenizer = HashTokenizer(int(self.knobs.get("vocab_size",
                                                              1 << 14)))

    # ---- internals ----
    def _module(self, quantized: bool = False, n_adapters: int = 0,
                seq_mesh: Any = None,
                seq_axis: Optional[str] = None,
                head_axis: Optional[str] = None,
                kv_page_size: int = 0, kv_pages: int = 0,
                paged_kernel: Optional[bool] = None) -> Llama:
        k = self.knobs
        hd = int(k["hidden_dim"])
        heads = int(k["n_heads"])
        kv_heads = max(1, heads // int(k["kv_ratio"]))
        return Llama(vocab_size=self.tokenizer.vocab_size,
                     max_len=int(k["max_len"]), hidden_dim=hd,
                     depth=int(k["depth"]), n_heads=heads,
                     n_kv_heads=kv_heads, mlp_dim=4 * hd,
                     lora_rank=int(k["lora_rank"]),
                     dtype=self._dtype(),
                     remat=bool(k.get("remat", False)),
                     remat_policy=str(k.get("remat_policy", "") or ""),
                     n_experts=int(k.get("moe_experts", 0)),
                     moe_top_k=int(k.get("moe_top_k", 1) or 1),
                     quantized=quantized, n_adapters=n_adapters,
                     seq_mesh=seq_mesh, seq_axis=seq_axis,
                     head_axis=head_axis,
                     rope_theta=float(k.get("rope_theta", 10000.0)
                                      or 10000.0),
                     rope_scaling=_parse_rope_scaling(
                         k.get("rope_scaling", "")),
                     kv_int8=bool(k.get("kv_cache_int8", False)),
                     kv_page_size=int(kv_page_size),
                     kv_pages=int(kv_pages),
                     paged_kernel=paged_kernel)

    def estimate_device_budget(self, n_devices: int,
                               gang_size: int = 0) -> Dict[str, int]:
        """Per-device train-step HBM budget for THIS parameterization on
        an ``n_devices`` mesh — the knob-level front of
        :func:`estimate_train_device_bytes` (admission control: a
        worker can refuse a trial whose ``total`` exceeds its chips'
        HBM instead of OOMing mid-step). Mesh factors derive exactly
        as :meth:`train` builds them: sp and model_parallel consume
        their factors, the rest is data parallelism.

        ``gang_size >= 1`` budgets a K-lane gang step instead
        (:func:`estimate_gang_device_bytes`): one broadcast base, K
        stacked adapter/optimizer lanes, unsharded — how the gang
        executor actually runs. 0 (the default) keeps the sequential
        mesh math."""
        if gang_size >= 1:
            return estimate_gang_device_bytes(
                self._module(),
                batch_size=int(self.knobs["batch_size"]),
                gang_size=int(gang_size),
                remat_policy=str(self.knobs.get("remat_policy", "")
                                 or ""),
                adapters_only=bool(self.knobs.get("adapters_only",
                                                  False)),
                overlap_collectives=bool(
                    self.knobs.get("overlap_collectives", False)))
        sp = int(self.knobs.get("sequence_parallel", 1) or 1)
        mp = int(self.knobs.get("model_parallel", 1) or 1)
        pp = int(self.knobs.get("pipeline_stages", 1) or 1)
        if pp > 1:
            # pipe x data mesh: batch shards over n/pp devices and
            # params REPLICATE (modeled by the pipeline estimator)
            sp, mp = 1, 1
            dp = max(1, n_devices // pp)
        else:
            if sp == 1:
                while n_devices % mp:
                    mp //= 2
                mp = max(1, mp)
            dp = max(1, n_devices // (sp * mp))
        return estimate_train_device_bytes(
            self._module(),
            batch_size=int(self.knobs["batch_size"]),
            data_parallel=dp, model_parallel=mp, sequence_parallel=sp,
            grad_accum=int(self.knobs.get("grad_accum", 1) or 1),
            loss_chunk=int(self.knobs.get("loss_chunk", 0) or 0),
            remat=bool(self.knobs.get("remat", False)),
            remat_policy=str(self.knobs.get("remat_policy", "") or ""),
            adapters_only=bool(self.knobs.get("adapters_only", False)),
            pipeline_stages=pp,
            pipeline_microbatches=int(
                self.knobs.get("pipeline_microbatches", 0) or 0),
            overlap_collectives=bool(
                self.knobs.get("overlap_collectives", False)))

    def estimate_serving_device_bytes(self, max_slots: int = 8,
                                      n_extra_adapters: int = 0,
                                      draft: Optional["LlamaLoRA"] = None,
                                      kv_page_size: int = 0,
                                      kv_pages: int = 0,
                                      host_kv_pages: int = 0
                                      ) -> Dict[str, int]:
        """Per-device HBM budget for the continuous-batching decode
        engine — the serving twin of :func:`estimate_train_device_bytes`
        (admission control: an inference worker can refuse a deployment
        whose engine would OOM at boot instead of dying mid-warmup).

        - ``params``: EXACT when the model is loaded — byte count of
          the actual serving tree (the int8 tree when ``quantize_int8``
          is set), else the abstract f32 init.
        - ``kv_cache``: max_slots x max_len x kv_heads x head_dim x
          2 (K and V) x depth, at int8+f32-scales when
          ``kv_cache_int8`` else the compute dtype. Multi-adapter
          serving shares ONE cache (the stacked engine batches
          tenants into the same slots). With ``kv_page_size > 0``
          (paged serving) the term is the POOL instead —
          kv_pages x kv_page_size positions per layer — which is the
          whole point: admission can budget live tokens, not
          max_slots x max_len.
        - ``adapters``: stacked LoRA tensors for extra tenants
          (adapter dims scale linearly in tenant count).
        - ``draft``: the draft model's params + its own KV cache when
          draft-model speculation is configured.
        - ``working``: prefill-chunk activations + one (slots, vocab)
          f32 logits buffer — the decode scan's live set.
        - ``host_kv_cache`` (``host_kv_pages > 0``): the pinned-host
          page tier's bytes — HOST RAM, reported for sizing but
          excluded from ``total`` (which stays the per-device HBM
          figure admission compares against chip memory).
        """
        k = self.knobs
        if int(host_kv_pages) and int(kv_page_size) <= 0:
            # mirror the engine-build rule so admission never blesses
            # a tier the engine constructor refuses
            raise ValueError("host_kv_pages requires kv_page_size > 0 "
                             "(pages are the host tier's transfer "
                             "unit)")
        hd, heads = int(k["hidden_dim"]), int(k["n_heads"])
        kv_heads = max(1, heads // int(k["kv_ratio"]))
        dh = hd // heads
        L, depth = int(k["max_len"]), int(k["depth"])
        act_bytes = 2 if bool(k.get("bf16", False)) else 4

        if self._params is not None:
            module, params = self._serving_module_params()
            params_dev = sum(
                int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                for l in jax.tree_util.tree_leaves(params))
            vocab = module.vocab_size
        else:
            module = self._module()
            abstract = jax.eval_shape(
                lambda: module.init(jax.random.PRNGKey(0),
                                    jnp.zeros((1, L), jnp.int32)))
            params_dev = sum(
                int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                for l in jax.tree_util.tree_leaves(abstract["params"]))
            vocab = module.vocab_size

        per_pos = kv_heads * dh
        if int(kv_page_size) > 0:
            # paged pool: kv_pages x page_size positions per layer
            # (exactly what DecodeEngine allocates), independent of
            # max_slots — the footprint the block-table design buys.
            # kv_pages=0 mirrors the engine's full-coverage default.
            # The engine's validity rules apply here too: admission
            # must never pass a budget for a pool the engine build
            # will refuse.
            if L % int(kv_page_size):
                raise ValueError(f"kv_page_size {kv_page_size} must "
                                 f"divide max_len {L}")
            if kv_pages and int(kv_pages) < 2:
                raise ValueError("paged KV needs kv_pages >= 2 "
                                 "(scratch page + at least one usable "
                                 "page)")
            n_pages = int(kv_pages) or _default_kv_pages(
                max_slots, L, int(kv_page_size))
            n_pos = n_pages * int(kv_page_size)
        else:
            n_pos = max_slots * L
        if bool(k.get("kv_cache_int8", False)):
            # int8 rows + one f32 absmax scale per (slot, pos, head)
            kv_dev = n_pos * depth * 2 * (per_pos + 4 * kv_heads)
        else:
            kv_dev = n_pos * depth * 2 * per_pos * act_bytes
        adapters_dev = 0
        if n_extra_adapters:
            rank = int(k.get("lora_rank", 0) or 0)
            # per LoRA site: a (in, r) + b (r, out); 7 sites per block
            # (wq/wk/wv/wo/gate/up/down) + lm_head — linear in tenants
            # 7 LoRA sites per block (wq/wk/wv/wo/gate/up/down); the
            # lm_head is built rank-0 (no adapters stack there)
            site_in_out = [
                (hd, heads * dh), (hd, kv_heads * dh), (hd, kv_heads * dh),
                (heads * dh, hd), (hd, 4 * hd), (hd, 4 * hd), (4 * hd, hd)]
            per_adapter = depth * sum(
                (i * rank + rank * o) * 4 for i, o in site_in_out)
            adapters_dev = n_extra_adapters * per_adapter
        draft_dev = 0
        if draft is not None:
            d = draft.estimate_serving_device_bytes(max_slots=max_slots)
            draft_dev = d["params"] + d["kv_cache"]
        working = (max_slots * 32 * hd * act_bytes  # prefill chunk
                   + max_slots * vocab * 4)         # logits buffer
        out = {"params": params_dev, "kv_cache": kv_dev,
               "adapters": adapters_dev, "draft": draft_dev,
               "working": working}
        out["total"] = sum(out.values())
        if int(host_kv_pages):
            # same per-position bytes as the device pool, host side —
            # after the total so the HBM figure is unchanged
            n_pos_host = int(host_kv_pages) * int(kv_page_size)
            if bool(k.get("kv_cache_int8", False)):
                out["host_kv_cache"] = n_pos_host * depth * 2 * (
                    per_pos + 4 * kv_heads)
            else:
                out["host_kv_cache"] = (n_pos_host * depth * 2
                                        * per_pos * act_bytes)
        return out

    def _serving_module_params(self, kv_page_size: int = 0,
                               kv_pages: int = 0,
                               paged_kernel: Optional[bool] = None
                               ) -> Tuple[Llama, Any]:
        """(module, params) for predict()/make_decode_engine — the int8
        pair when the quantize_int8 knob is set (quantized once per
        trained tree, then cached). Paging fields shape only the decode
        CACHE, never the params, so any (kv_page_size, kv_pages,
        paged_kernel) triple serves the same trained tree."""
        if not self.knobs.get("quantize_int8"):
            return self._module(kv_page_size=kv_page_size,
                                kv_pages=kv_pages,
                                paged_kernel=paged_kernel), self._params
        if self._qparams is None:
            self._qparams = quantize_llama_params(self._params)
        return self._module(quantized=True, kv_page_size=kv_page_size,
                            kv_pages=kv_pages,
                            paged_kernel=paged_kernel), self._qparams

    def _dtype(self):
        # single source of truth for the bf16 knob → compute dtype
        # (params stay f32; the matmul-heavy layers run in this dtype)
        return jnp.bfloat16 if self.knobs.get("bf16", True) else None

    @property
    def _bpe(self) -> bool:
        """True when a real (invertible) tokenizer is active."""
        return hasattr(self.tokenizer, "decode")

    def _encode_lm(self, texts: Sequence[str]) -> Tuple[np.ndarray,
                                                        np.ndarray]:
        """BOS-prefixed token rows. With the hash tokenizer this also
        grows the id→token table used to detokenize generations (hashing
        is one-way); BPE decodes exactly and needs no table."""
        max_len = int(self.knobs["max_len"])
        ids = np.zeros((len(texts), max_len), np.int32)
        lens = np.zeros((len(texts),), np.int32)
        for i, t in enumerate(texts):
            row, n = self.tokenizer.encode(t, max_len)  # CLS slot = BOS
            ids[i], lens[i] = row, n
            if not self._bpe:
                # mirror the tokenizer's own splitting so ids align
                # with words
                for tok_str, tok_id in zip(_TOKEN_RE.findall(t.lower()),
                                           row[1:n]):
                    self._id2tok[int(tok_id)] = tok_str
        return ids, lens

    def _mesh(self, devices):
        n = len(devices)
        mp = int(self.knobs.get("model_parallel", 1))
        while n % mp:
            mp //= 2
        return make_mesh(devices, model=max(1, mp))

    # ---- gang-compiled tuning (vmapped LoRA lanes) ----
    @classmethod
    def gang_blockers(cls, knobs: Knobs) -> List[str]:
        """Why THIS assignment cannot train as a gang lane (empty list
        = gangable). A lane is one unsharded program over a broadcast
        base, so every in-trial parallelism / accumulation regime —
        and a pretrained base, since lanes share the PRNGKey(0) init —
        stays on the sequential mesh path. Each entry names the
        blocking knob; ``tune_model``'s fallback warning surfaces them
        so an operator knows what to pin."""
        def _i(name: str, default: int = 0) -> int:
            return int(knobs.get(name, default) or default)

        out: List[str] = []
        if _i("model_parallel", 1) > 1:
            out.append("model_parallel>1 (tensor parallelism needs the "
                       "sharded mesh path)")
        if _i("sequence_parallel", 1) > 1:
            out.append("sequence_parallel>1 (sp shards activations over "
                       "a mesh the lane step does not build)")
        if _i("pipeline_stages", 1) > 1:
            out.append("pipeline_stages>1 (GPipe owns the device set)")
        if _i("grad_accum", 1) > 1:
            out.append("grad_accum>1 (the accumulation scan is not "
                       "factored into the lane step)")
        if _i("moe_experts") > 0:
            out.append("moe_experts>0 (expert parallelism + aux-loss "
                       "sow need the mesh path)")
        if _i("loss_chunk") > 0:
            out.append("loss_chunk>0 (the streamed loss is not factored "
                       "into the lane step)")
        if str(knobs.get("pretrained_path") or ""):
            out.append("pretrained_path set (lanes broadcast the shared "
                       "PRNGKey(0) base; checkpoint import is a mesh-"
                       "path feature)")
        return out

    @classmethod
    def gang_epochs(cls, knobs: Knobs, budget_scale: float) -> int:
        """Epoch count ``train()`` spends for this assignment — the gang
        scheduler's per-lane budget (must mirror the sequential loop
        exactly, quick_train cap included)."""
        epochs = max(1, round(int(knobs["max_epochs"])
                              * float(budget_scale)))
        if knobs.get("quick_train"):
            epochs = min(epochs, 2)
        return epochs

    @staticmethod
    def _lane_functions(module: "Llama", base_params: Any,
                        adapters_only: bool):
        """``(init_lane, train_step, eval_lane, merge, split)`` — the
        functional training core shared by the sequential
        ``_train_functional`` loop and the gang engine's vmapped lanes
        (1 lane == 1 sequential trial, bit-for-bit).

        The frozen base rides as a CLOSURE: under ``jax.vmap`` a
        closed-over tree is broadcast (``in_axes=None`` semantics), so
        K lanes share ONE HBM copy of the base while only the trainable
        leaves — a flat ``{path: leaf}`` dict — and their Adam state
        stack on the lane axis. ``hp`` carries the traceable knobs as
        traced scalars: ``optax.adamw(lr)`` is exactly
        ``scale_by_adam → add_decayed_weights → scale(-lr)``, so
        applying ``-lr`` to the decayed adam updates keeps the math
        identical while lr differs per lane inside one compiled
        program; ``lora_scale`` multiplies every ``lora_b`` leaf inside
        ``merge`` (the LoRA α/r rank-scale), and the export path folds
        the SAME elementwise product into the stored tree, so serving
        needs no scale plumbing and scale=1 is the legacy forward
        bit-for-bit."""
        mask_fn = adapter_only_mask if adapters_only \
            else lora_trainable_mask
        flat = jax.tree_util.tree_flatten_with_path(base_params)[0]
        flat_m = jax.tree_util.tree_leaves(mask_fn(base_params))
        paths = {_kp_path(kp) for (kp, _), m in zip(flat, flat_m) if m}
        tx = optax.chain(optax.scale_by_adam(),
                         optax.add_decayed_weights(1e-4))

        def split(tree: Any) -> Dict[str, Any]:
            return {_kp_path(kp): leaf for kp, leaf in
                    jax.tree_util.tree_flatten_with_path(tree)[0]
                    if _kp_path(kp) in paths}

        def merge(trainable: Dict[str, Any],
                  hp: Dict[str, Any]) -> Any:
            scale = hp["lora_scale"]

            def fill(kp, leaf):
                p = _kp_path(kp)
                if p not in paths:
                    return leaf  # frozen base — broadcast under vmap
                t = trainable[p]
                return scale * t if "lora_b" in p else t

            return jax.tree_util.tree_map_with_path(fill, base_params)

        def init_lane(rng: Any, hp: Dict[str, Any]) -> Dict[str, Any]:
            t = split(base_params)
            return {"params": t, "opt": tx.init(t)}

        def train_step(state: Dict[str, Any], hp: Dict[str, Any],
                       batch: Dict[str, Any]):
            def loss_fn(t):
                p = merge(t, hp)
                logits = module.apply({"params": p}, batch["ids"],
                                      lens=batch["lens"])
                total, count = lm_loss_terms(logits, batch["ids"],
                                             batch["lens"],
                                             batch["mask"])
                return total / jnp.maximum(count, 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(state["params"])
            updates, opt = tx.update(grads, state["opt"],
                                     state["params"])
            updates = jax.tree_util.tree_map(
                lambda u: -hp["learning_rate"] * u, updates)
            return {"params": optax.apply_updates(state["params"],
                                                  updates),
                    "opt": opt}, loss

        def eval_lane(state: Dict[str, Any], hp: Dict[str, Any],
                      batch: Dict[str, Any]):
            p = merge(state["params"], hp)
            logits = module.apply({"params": p}, batch["ids"],
                                  lens=batch["lens"])
            return lm_loss_terms(logits, batch["ids"], batch["lens"])

        return init_lane, train_step, eval_lane, merge, split

    @classmethod
    def make_gang_spec(cls, knobs: Knobs, train_dataset_path: str,
                       val_dataset_path: str) -> GangSpec:
        """Functional training recipe for the gang engine: K LoRA
        adapter sets (+ Adam state) as lanes of one vmapped step over
        ONE broadcast frozen base. Everything but ``learning_rate`` /
        ``lora_scale`` (the traceable knobs) is burned in from
        ``knobs``; ``remat_policy`` and ``overlap_collectives`` are
        static, so each schedule is its own compile bucket."""
        blockers = cls.gang_blockers(knobs)
        if blockers:
            raise ValueError("knobs block gang lanes: "
                             + "; ".join(blockers))
        model = cls(**knobs)  # tokenizer wiring (vocab / BPE artifact)
        ds = load_text_classification_dataset(train_dataset_path)
        ids, lens = model._encode_lm(ds.texts)
        vds = load_text_classification_dataset(val_dataset_path)
        vids, vlens = model._encode_lm(vds.texts)
        module = model._module()
        batch_size = int(knobs["batch_size"])
        base = module.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, ids.shape[1]),
                                     jnp.int32))["params"]
        init_lane, train_step, eval_lane, merge, _split = \
            cls._lane_functions(module, base,
                                bool(knobs.get("adapters_only", False)))
        meta: Dict[str, Any] = {
            "id2tok": {str(k): v for k, v in model._id2tok.items()}}
        if model._bpe:
            meta["bpe_merges"] = [list(m)
                                  for m in model.tokenizer.merges]

        def epoch_batches(epoch: int):
            return batch_iterator({"ids": ids, "lens": lens},
                                  batch_size, seed=epoch)

        def eval_batches():
            # the SAME bucket-32 zero-padded stream evaluate() walks
            # (padded rows have lens=0, so no loss position counts)
            bucket = 32
            for i in range(0, len(vids), bucket):
                ib, lb = vids[i:i + bucket], vlens[i:i + bucket]
                pad = bucket - len(ib)
                if pad:
                    ib = np.concatenate(
                        [ib, np.zeros((pad, vids.shape[1]), ib.dtype)])
                    lb = np.concatenate(
                        [lb, np.zeros((pad,), lb.dtype)])
                yield {"ids": ib, "lens": lb}

        @jax.jit
        def _nll(params, ib, lb):
            logits = module.apply({"params": params}, ib, lens=lb)
            return lm_loss_terms(logits, ib, lb)

        def eval_seq(lane_state, hp, batch):
            # score on the graph evaluate() compiles: fold the lane's
            # rank-scale EAGERLY (exact elementwise product), then run
            # the same full-params nll jit — merging inside a vmapped
            # eval re-fuses the forward and drifts in the low bits
            p = merge(lane_state["params"], hp)
            return _nll(p, batch["ids"], batch["lens"])

        def export_blob(lane_state, hp):
            # fold the lane's rank-scale into lora_b — the same
            # elementwise product the train forward applied, so the
            # stored tree serves scale-free and token-identically
            # (dump_parameters format: make_multi_adapter_engine /
            # load_parameters load it as-is)
            hp_dev = {"learning_rate": jnp.float32(
                          float(hp["learning_rate"])),
                      "lora_scale": jnp.float32(
                          float(hp["lora_scale"]))}
            folded = merge({k: jnp.asarray(v) for k, v in
                            lane_state["params"].items()}, hp_dev)
            return {"params": jax.tree_util.tree_map(np.asarray,
                                                     folded),
                    "meta": dict(meta)}

        def warm_lane(fresh, blob):
            shared = (blob or {}).get("params")
            if shared is None or not same_tree_shapes(base, shared):
                return fresh  # incompatible architecture → cold start
            # adopt the parent's trainable leaves; the frozen base is
            # already this spec's broadcast copy (pretrained bases are
            # gang blockers, so both inits are PRNGKey(0))
            return {"params": _split(jax.tree_util.tree_map(
                        jnp.asarray, shared)),
                    "opt": fresh["opt"]}

        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(base))
        return GangSpec(
            hp_names=("learning_rate", "lora_scale"),
            init_lane=init_lane, train_step=train_step,
            epoch_batches=epoch_batches, eval_lane=eval_lane,
            eval_batches=eval_batches, export_blob=export_blob,
            warm_lane=warm_lane, share_params_knob="share_params",
            score_kind="lm", tokens_per_sample=int(knobs["max_len"]),
            lane_param_count=n_params,
            compiler_options=overlap_compiler_options(
                bool(knobs.get("overlap_collectives", False))) or None,
            eval_seq=eval_seq)

    def _train_functional(self, ids: np.ndarray, lens: np.ndarray,
                          ctx: TrainContext) -> None:
        """The gang-compatible sequential loop: drives the SAME
        ``_lane_functions`` the gang engine vmaps, unvmapped — a 1-lane
        gang trial is this loop bit-for-bit (``jit(f)`` vs
        ``jit(vmap(f))`` at K=1; tier-1 asserts score equality).
        ``train()`` routes here whenever ``gang_blockers`` is empty;
        parallel / accumulation / pretrained regimes keep the legacy
        sharded mesh loop."""
        module = self._module()
        batch_size = int(self.knobs["batch_size"])
        base = module.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, ids.shape[1]),
                                     jnp.int32))["params"]
        if self._params is not None and \
                same_tree_shapes(base, self._params):
            # re-train / load_parameters: current weights are the init
            base = jax.tree_util.tree_map(jnp.asarray, self._params)
        if ctx.shared_params is not None and \
                self.knobs.get("share_params"):
            if hasattr(ctx.shared_params, "restore"):
                import logging

                logging.getLogger(__name__).warning(
                    "sharded warm-start handles target the mesh train "
                    "path; the functional (gang-compatible) path "
                    "cold-starts")
            else:
                shared = ctx.shared_params.get("params")
                if shared is not None and same_tree_shapes(base,
                                                           shared):
                    base = jax.tree_util.tree_map(jnp.asarray, shared)
        init_lane, _train_step, _eval_lane, merge, _split = \
            self._lane_functions(
                module, base,
                bool(self.knobs.get("adapters_only", False)))
        hp = {"learning_rate": jnp.float32(
                  float(self.knobs["learning_rate"])),
              "lora_scale": jnp.float32(
                  float(self.knobs.get("lora_scale", 1.0)))}
        state = init_lane(jax.random.PRNGKey(0), hp)
        if ctx.devices:
            # the worker pins trials to disjoint device slots:
            # committing the state pulls the whole step onto the
            # slot's first device
            state = jax.device_put(state, ctx.devices[0])
        step = jax.jit(
            _train_step, donate_argnums=(0,),
            compiler_options=overlap_compiler_options(
                bool(self.knobs.get("overlap_collectives",
                                    False))) or None)
        epochs = self.gang_epochs(self.knobs, ctx.budget_scale)
        ctx.logger.define_plot("LM loss", ["loss"], x_axis="epoch")
        # donation invalidates buffers aliasing self._params (warm
        # start / re-train): drop the stale references first
        self._params = None
        self._qparams = None
        for epoch in range(epochs):
            losses = []
            for batch in batch_iterator({"ids": ids, "lens": lens},
                                        batch_size, seed=epoch):
                state, loss = step(state, hp, batch)
                losses.append(loss)
            mean_loss = (float(np.mean([float(l) for l in losses]))
                         if losses else float("nan"))
            ctx.logger.log(epoch=epoch, loss=mean_loss,
                           tokens=int(ids.shape[0] * ids.shape[1]))
            if ctx.checkpoint is not None:
                # preemption safety: worker throttles + persists. The
                # stored tree is the FOLDED merge (scale into lora_b),
                # the same shape dump_parameters always produced
                self._params = merge(state["params"], hp)
                ctx.checkpoint(self.dump_parameters,
                               frac_done=(epoch + 1) / epochs,
                               tree={"params": self._params})
            if ctx.should_continue is not None and \
                    not ctx.should_continue(epoch, -mean_loss):
                break
        self._params = merge(state["params"], hp)
        self._qparams = None
        self._fwd = None

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = load_text_classification_dataset(dataset_path)
        ids, lens = self._encode_lm(ds.texts)

        if not self.gang_blockers(self.knobs):
            # unsharded single-program regime: run the functional loop
            # the gang engine vmaps, so a sequential trial and a gang
            # lane are the same computation (bit-exactness contract)
            return self._train_functional(ids, lens, ctx)

        module = self._module()
        devices = ctx.devices or jax.local_devices()
        mesh = self._mesh(devices)
        sp = int(self.knobs.get("sequence_parallel", 1) or 1)
        sp_tp = 1  # model-parallel degree composed WITH sp (3-axis mesh)
        if sp > 1:
            # sequence parallelism: (data, sp[, model]) mesh, every
            # (B, L) operand's L sharded over `sp`, attention via
            # ulysses all-to-alls — or ring K/V rotation when per-shard
            # heads don't divide sp (module seq_mesh/seq_axis; dispatch
            # in _DecoderAttention). Long-context regime — each device
            # holds L/sp of every activation. With model_parallel>1 the
            # mesh gains a third `model` axis: Megatron TP per TP_RULES
            # shards the head dim, and the sp collectives run WITHIN
            # each TP head group (SURVEY §2.2's v5e-16 stretch config —
            # a long-context 8B job needs sp composed with tp).
            from jax.sharding import Mesh

            sp_tp = int(self.knobs.get("model_parallel", 1) or 1)
            if int(self.knobs.get("pipeline_stages", 1) or 1) > 1:
                raise ValueError(
                    "sequence_parallel>1 is mutually exclusive with "
                    "pipeline_stages>1 (pick sp[×tp]×dp or pp×dp)")
            if int(self.knobs.get("moe_experts", 0)) and sp_tp == 1:
                raise ValueError(
                    "moe_experts with sequence_parallel requires "
                    "model_parallel>1: experts shard over the `model` "
                    "axis, which the dp x sp mesh lacks (the 3-axis "
                    "dp x sp x model mesh carries both)")
            if int(self.knobs.get("loss_chunk", 0) or 0) and sp_tp > 1:
                raise ValueError(
                    "loss_chunk with sequence_parallel requires "
                    "model_parallel=1 (the sharded chunked loss keeps "
                    "the head replicated; a vocab-sharded head inside "
                    "the shard would need cross-axis softmax)")
            if len(devices) % (sp * sp_tp):
                raise ValueError(
                    f"sequence_parallel={sp} x model_parallel={sp_tp} "
                    f"must divide the trial's {len(devices)} devices")
            # per-shard n_heads % sp == 0 -> ulysses (2 all-to-alls);
            # otherwise the attention auto-falls-back to ring rotation
            # (P ppermutes) — see _DecoderAttention. Both are exact.
            if int(self.knobs["max_len"]) % sp:
                raise ValueError(f"max_len {self.knobs['max_len']} must "
                                 f"divide by sequence_parallel={sp}")
            heads = int(self.knobs["n_heads"])
            kv_heads = max(1, heads // int(self.knobs["kv_ratio"]))
            if sp_tp > 1 and (heads % sp_tp or kv_heads % sp_tp):
                raise ValueError(
                    f"sequence_parallel with model_parallel={sp_tp} "
                    f"needs n_heads ({heads}) and kv heads ({kv_heads}) "
                    "divisible by it (TP shards whole heads)")
            if sp_tp > 1:
                mesh = Mesh(
                    np.array(devices, dtype=object).reshape(-1, sp, sp_tp),
                    (DATA_AXIS, "sp", MODEL_AXIS))
                module = self._module(seq_mesh=mesh, seq_axis="sp",
                                      head_axis=MODEL_AXIS)
            else:
                mesh = Mesh(
                    np.array(devices, dtype=object).reshape(-1, sp),
                    (DATA_AXIS, "sp"))
                module = self._module(seq_mesh=mesh, seq_axis="sp")
        pp_stages = int(self.knobs.get("pipeline_stages", 1) or 1)
        n_micro = int(self.knobs.get("pipeline_microbatches", 0)
                      or 0) or pp_stages
        mesh_pp = None
        if pp_stages > 1:
            from jax.sharding import Mesh

            if int(self.knobs.get("model_parallel", 1)) > 1:
                # fail fast: the pipe×data mesh consumes every device,
                # so a requested TP regime would be silently dropped
                raise ValueError(
                    "pipeline_stages>1 is mutually exclusive with "
                    "model_parallel>1 (pick pp×dp or tp×fsdp)")
            if len(devices) % pp_stages:
                raise ValueError(
                    f"pipeline_stages={pp_stages} must divide the "
                    f"trial's {len(devices)} devices")
            if int(self.knobs["depth"]) % pp_stages:
                raise ValueError(
                    f"depth {self.knobs['depth']} must divide by "
                    f"pipeline_stages={pp_stages}")
            if n_micro % pp_stages:
                raise ValueError(
                    f"pipeline_microbatches={n_micro} must be a "
                    f"multiple of pipeline_stages={pp_stages}")
            # pipe × data over ALL trial devices (one device set for the
            # whole train step — params/batches live on this mesh too):
            # stages down one axis, each microbatch's batch dim sharded
            # over the other
            mesh_pp = Mesh(
                np.array(devices, dtype=object).reshape(
                    pp_stages, len(devices) // pp_stages),
                ("pipe", "data"))
        grad_accum = int(self.knobs.get("grad_accum", 1) or 1)
        if grad_accum > 1 and pp_stages > 1:
            raise ValueError(
                "grad_accum>1 is redundant with pipeline_stages>1 "
                "(GPipe already microbatches the step)")
        n_experts = int(self.knobs.get("moe_experts", 0))
        if n_experts and pp_stages > 1:
            raise ValueError("pipeline_stages>1 does not support MoE "
                             "blocks yet (aux loss cannot sow through "
                             "the pipeline scan)")
        if n_experts and n_experts % mesh.shape[MODEL_AXIS]:
            # fail fast: an indivisible expert count would silently fall
            # through the "experts" TP rule to the dense gate/up/down
            # rules — a mixed tensor-parallel regime instead of expert
            # parallelism, with a different collective/memory profile
            raise ValueError(
                f"moe_experts={n_experts} must be divisible by the "
                f"mesh's model axis ({mesh.shape[MODEL_AXIS]})")
        b_shard = batch_sharding(mesh)
        if sp > 1:
            # per-leaf shardings: (B, L) operands shard L over `sp`
            # (ids and the loss mask); per-example lens shard batch only
            from jax.sharding import NamedSharding, PartitionSpec

            batch1d = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
            b_shard = {"ids": NamedSharding(
                mesh, PartitionSpec(DATA_AXIS, "sp")),
                "lens": batch1d, "m": batch1d}  # lens/mask: per-example

        n_data = mesh.shape[DATA_AXIS]
        batch_size = int(self.knobs["batch_size"])
        batch_size = max(n_data, batch_size - batch_size % n_data)
        if mesh_pp is not None:
            # n_micro microbatches, each batch-sharded over `data`
            # (size devices/pp) → batch must divide by both
            q = int(np.lcm(n_micro, len(devices)))
            batch_size = max(q, batch_size - batch_size % q)
        if grad_accum > 1:
            # each micro-batch still batch-shards over `data`
            q = grad_accum * n_data
            batch_size = max(q, batch_size - batch_size % q)

        pretrained = str(self.knobs.get("pretrained_path") or "")
        fresh = self._params is None
        if fresh:
            # init through the PLAIN module even in sp mode: ulysses
            # adds no params, and its shard_map would reject the
            # single-row init trace (batch 1 can't shard over `data`)
            init_module = self._module() if sp > 1 else module
            params = init_module.init(jax.random.PRNGKey(0),
                                      jnp.zeros((1, ids.shape[1]),
                                                jnp.int32))["params"]
        else:
            params = self._params
        warm = False
        shared_ref = None
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            if hasattr(ctx.shared_params, "restore"):
                # sharded-checkpoint handle (store/sharded_ckpt.py):
                # gate on the manifest-only shape probe (the sharded
                # twin of same_tree_shapes — a mismatched donor must
                # leave warm=False so a pretrained base still loads),
                # then restore AFTER placement, straight into the 2-D
                # shardings: the warm tree never assembles on a host
                if ctx.shared_params.matches({"params": params}):
                    shared_ref = ctx.shared_params
                    warm = True
            else:
                shared = ctx.shared_params.get("params")
                if shared is not None and same_tree_shapes(params, shared):
                    params = jax.tree_util.tree_map(jnp.asarray, shared)
                    warm = True

        if pretrained and fresh and not warm:
            # base weights from an HF-convention checkpoint, loaded
            # DIRECTLY into their 2-D shardings (shard-sized file reads;
            # LoRA adapters keep their init) — config #5's real base.
            # A warm start / re-train already carries trained state and
            # must not be clobbered back to the checkpoint.
            from rafiki_tpu.models.convert import (import_llama_safetensors,
                                                   read_hf_rope_config)

            cfg_theta, cfg_scaling = read_hf_rope_config(pretrained)
            # the theta the model ACTUALLY uses (single source of
            # truth: _module's resolution), not a re-derivation
            knob_theta = module.rope_theta
            if cfg_theta is not None and \
                    abs(cfg_theta - knob_theta) > 1e-6:
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint config.json says rope_theta=%s but the "
                    "rope_theta knob is %s — a mismatched theta loads "
                    "cleanly and generates GARBAGE; set the knob to "
                    "match the checkpoint", cfg_theta, knob_theta)
            have = module.rope_scaling
            if cfg_scaling or have is not None:
                # symmetric check: scaling declared but not applied,
                # applied but not declared, mismatched, or of a TYPE
                # this model can't honor (yarn/linear/...) — all the
                # same silent-degradation class
                want = None
                unsupported = False
                if cfg_scaling:
                    try:
                        want = _parse_rope_scaling(cfg_scaling)
                    except (ValueError, TypeError):
                        unsupported = True
                if unsupported or (have is None) != (want is None) or (
                        have is not None and want is not None and any(
                            abs(a - b) > 1e-6
                            for a, b in zip(have, want))):
                    import logging

                    logging.getLogger(__name__).warning(
                        "checkpoint config.json rope_scaling=%r but "
                        "the rope_scaling knob resolves to %r — set "
                        "the knob to the checkpoint's values (or clear "
                        "it) or long-context generations silently "
                        "degrade", cfg_scaling, have)
            params = import_llama_safetensors(
                pretrained, params, mesh=mesh,
                tp_rules=None if (sp > 1 and sp_tp == 1) else TP_RULES,
                fsdp=True, min_size=2 ** 12)
        # 2-D sharding: tensor-parallel per TP_RULES over `model`, fsdp
        # over `data` for everything of >=4k elements — smaller tensors
        # (and test-scale params) are replicated, where fsdp's gather
        # traffic outweighs the memory it saves. The fsdp code path at
        # tiny shapes is covered by __graft_entry__.dryrun_multichip
        # (min_size=0 there). Imported leaves already sit in these
        # shardings (device_put is then a no-op); the put places the
        # rest (LoRA adapters, fresh/warm trees).
        if mesh_pp is not None:
            # pipeline mode: params live replicated on the pipe×data
            # mesh (ONE device set for the jitted step); the pipeline
            # re-annotates the block stacks onto their stages in-jit.
            # This is the activations-bound regime; a pretrained base
            # imported sharded above gets gathered here — weight-
            # sharded pipeline storage is future work, so flag it
            from jax.sharding import NamedSharding, PartitionSpec

            if pretrained:
                import logging

                logging.getLogger(__name__).warning(
                    "pipeline mode replicates the pretrained base on "
                    "every device; use tp×fsdp (pipeline_stages=1) "
                    "when WEIGHTS are the memory bound")
            rep_pp = NamedSharding(mesh_pp, PartitionSpec())
            params = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, rep_pp), params)
            b_shard = rep_pp
        else:
            # dp-only sp mesh has no `model` axis: fsdp-over-data only
            # (the sp regime is activations-bound; adapters are tiny
            # anyway). The sp×tp 3-axis mesh applies full TP_RULES.
            p_shard = param_shardings(
                params, mesh, tp_rules=None if (sp > 1 and sp_tp == 1)
                else TP_RULES,
                fsdp=True, min_size=2 ** 12)
            params = jax.tree_util.tree_map(jax.device_put, params,
                                            p_shard)
        if shared_ref is not None:
            try:
                params = shared_ref.restore({"params": params})["params"]
            except (KeyError, ValueError):
                import logging

                # shape/structure mismatch (different knobs) — cold
                # start, mirroring the same_tree_shapes guard above
                logging.getLogger(__name__).warning(
                    "sharded warm-start checkpoint does not match this "
                    "parameterization; training cold", exc_info=True)

        lr = float(self.knobs["learning_rate"])
        # multi_transform (not optax.masked): masked leaves pass raw
        # gradients through as updates, set_to_zero actually freezes
        mask_fn = (adapter_only_mask
                   if bool(self.knobs.get("adapters_only", False))
                   else lora_trainable_mask)
        tx = optax.multi_transform(
            {"train": optax.adamw(lr), "freeze": optax.set_to_zero()},
            lambda p: jax.tree_util.tree_map(
                lambda t: "train" if t else "freeze", mask_fn(p)))
        opt_state = tx.init(params)

        # donate the param/opt trees: in-place update, no per-step copies
        from rafiki_tpu.ops.moe import MOE_AUX_COEF, moe_aux_loss

        use_remat = bool(self.knobs.get("remat", False))
        loss_chunk = int(self.knobs.get("loss_chunk", 0) or 0)
        if loss_chunk and mesh_pp is not None:
            # the pipelined forward assembles logits stage-wise; wiring
            # the streamed loss through it is a separate change — fail
            # fast rather than silently ignore the knob
            raise ValueError("loss_chunk>0 is not supported with "
                             "pipeline_stages>1")

        def micro_terms(p, ib, lb, mask):
            # (loss-sum, valid-count, moe-aux) over one (micro)batch —
            # shared by the plain step and gradient accumulation
            if loss_chunk:
                # streamed loss: forward stops at the final norm; the
                # lm_head projection + CE run chunk-by-chunk so
                # (B, L, vocab) logits never exist in HBM
                hidden, muts = module.apply(
                    {"params": p}, ib, lens=lb, mutable=["losses"],
                    return_hidden=True)
                aux = moe_aux_loss(muts)
                if sp > 1:
                    # long-context composition: hidden's L is sharded
                    # over `sp` — stream each shard's own chunks and
                    # psum (no per-chunk re-gather)
                    total, count = chunked_lm_loss_terms_sp(
                        hidden, p["lm_head"]["kernel"], ib, lb, mask,
                        loss_chunk, mesh, DATA_AXIS, "sp")
                else:
                    total, count = chunked_lm_loss_terms(
                        hidden, p["lm_head"]["kernel"], ib, lb, mask,
                        chunk=loss_chunk)
            else:
                # mutable=["losses"]: MoE blocks sow their load-
                # balance aux there; dense models sow nothing
                logits, muts = module.apply(
                    {"params": p}, ib, lens=lb, mutable=["losses"])
                aux = moe_aux_loss(muts)
                total, count = lm_loss_terms(logits, ib, lb, mask)
            return total, count, aux

        @functools.partial(
            jax.jit, donate_argnums=(0, 1),
            compiler_options=overlap_compiler_options(
                bool(self.knobs.get("overlap_collectives",
                                    False))) or None)
        def train_step(params, opt_state, ib, lb, mask):
            if grad_accum > 1:
                # gradient accumulation: scan grad_accum micro-batches,
                # summing gradients before ONE optimizer step. The CE
                # term is EXACTLY the big-batch math: the global valid-
                # token count is model-independent, so each micro-
                # batch's objective is total_i / global_count — summed
                # grads == grads of the full-batch loss. The MoE aux
                # (when moe_experts > 0) is computed per micro-batch
                # and averaged — standard practice, but router capacity
                # and load statistics then see T/grad_accum tokens, so
                # that term is NOT bit-identical to one big-batch apply.
                b, seq = ib.shape
                denom = jnp.maximum(jnp.sum(
                    lm_valid_mask(seq, lb, mask)).astype(jnp.float32),
                    1.0)
                mbs = (ib.reshape(grad_accum, b // grad_accum, seq),
                       lb.reshape(grad_accum, b // grad_accum),
                       mask.reshape(grad_accum, b // grad_accum))

                def obj(p, i, l, m):
                    total, _, aux = micro_terms(p, i, l, m)
                    return (total / denom
                            + MOE_AUX_COEF * aux / grad_accum)

                def body(carry, xs):
                    gacc, lacc = carry
                    val, g = jax.value_and_grad(obj)(params, *xs)
                    return (jax.tree_util.tree_map(jnp.add, gacc, g),
                            lacc + val), None

                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, loss), _ = jax.lax.scan(
                    body, (zeros, jnp.asarray(0.0, jnp.float32)), mbs)
                updates, opt_state = tx.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state,
                        loss)

            def loss_fn(p):
                if mesh_pp is not None:
                    # decoder blocks pipelined over the `pipe` axis —
                    # identical math to the canonical forward (proven by
                    # tests/test_pipeline.py); MoE rejected upstream
                    logits = pipelined_lm_forward(
                        module, p, ib, lb, mesh_pp, n_micro=n_micro,
                        remat=use_remat, batch_axis="data")
                    aux = jnp.asarray(0.0, jnp.float32)
                    total, count = lm_loss_terms(logits, ib, lb, mask)
                else:
                    total, count, aux = micro_terms(p, ib, lb, mask)
                return (total / jnp.maximum(count, 1.0)
                        + MOE_AUX_COEF * aux)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        epochs = self.gang_epochs(self.knobs, ctx.budget_scale)
        def step(state, b):
            params, opt_state = state
            params, opt_state, loss = train_step(
                params, opt_state, b["ids"], b["lens"], b["m"])
            return (params, opt_state), loss

        ctx.logger.define_plot("LM loss", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._params (warm
        # start / re-train): drop the stale references first
        self._params = None
        self._qparams = None
        with mesh:
            for epoch in range(epochs):
                (params, opt_state), mean_loss = train_epoch(
                    step, (params, opt_state),
                    ({"ids": b["ids"], "lens": b["lens"],
                      "m": b["mask"].astype(np.float32)}
                     for b in batch_iterator({"ids": ids, "lens": lens},
                                             batch_size, seed=epoch)),
                    sharding=b_shard)
                # tokens: the epoch's (padded) token volume — the train
                # worker's obs hook turns it into tokens/s + est_mfu so
                # trials compare on throughput, not just loss
                ctx.logger.log(epoch=epoch, loss=mean_loss,
                               tokens=int(ids.shape[0] * ids.shape[1]))
                if ctx.checkpoint is not None:
                    # preemption safety: worker throttles + persists.
                    # The live (sharded device) tree rides along so a
                    # sharded-capable store saves per-shard + async —
                    # the blob factory only runs on fallback backends
                    self._params = params
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs,
                                   tree={"params": params})
                if ctx.should_continue is not None and \
                        not ctx.should_continue(epoch, -mean_loss):
                    break
        self._params = params
        self._qparams = None
        self._fwd = None

    def evaluate(self, dataset_path: str) -> float:
        """Inverse perplexity exp(-nll) in (0, 1]; higher is better."""
        assert self._params is not None
        ds = load_text_classification_dataset(dataset_path)
        ids, lens = self._encode_lm(ds.texts)
        if self._fwd is None:  # cache: jit memoizes by function identity
            module = self._module()
            loss_chunk = int(self.knobs.get("loss_chunk", 0) or 0)

            @jax.jit
            def nll(params, ib, lb):
                if loss_chunk:
                    # a config that NEEDS the streamed loss to train
                    # (vocab·L logits over HBM) would OOM right here at
                    # eval otherwise — same chunking, same math
                    hidden = module.apply({"params": params}, ib, lens=lb,
                                          return_hidden=True)
                    return chunked_lm_loss_terms(
                        hidden, params["lm_head"]["kernel"], ib, lb,
                        chunk=loss_chunk)
                logits = module.apply({"params": params}, ib, lens=lb)
                return lm_loss_terms(logits, ib, lb)

            self._fwd = nll
        nll = self._fwd
        total, count = 0.0, 0.0
        bucket = 32
        for i in range(0, len(ids), bucket):
            ib, lb = ids[i:i + bucket], lens[i:i + bucket]
            pad = bucket - len(ib)
            if pad:
                ib = np.concatenate([ib, np.zeros((pad, ids.shape[1]),
                                                  ib.dtype)])
                lb = np.concatenate([lb, np.zeros((pad,), lb.dtype)])
            s, c = nll(self._params, ib, lb)
            total += float(s)
            count += float(c)
        return float(np.exp(-total / max(count, 1.0)))

    def predict(self, queries: Sequence[Any],
                max_new_tokens: int = 8) -> List[Any]:
        """Greedy continuations, detokenized via the learned id→token
        table (unknown ids render as ``<id>``).

        The batch dim is padded up to a power-of-two bucket so repeated
        serving calls reuse the compiled generate (static module +
        max_new, bucketed (b, prompt) shapes → executable-cache hits)."""
        assert self._params is not None, "model is not trained/loaded"
        texts = [q if isinstance(q, str) else str(q) for q in queries]
        max_len = int(self.knobs["max_len"])
        # the KV cache holds max_len positions total (prompt + generation)
        max_new = min(max_new_tokens, max_len - 1)
        prompt_cap = max(1, max_len - max_new)
        ids, lens = self.tokenizer.encode_batch(texts, prompt_cap)
        n = len(texts)
        bucket = 1 << max(0, (n - 1).bit_length())  # next power of two
        if bucket > n:  # pad rows are BOS-only prompts, discarded below
            ids = np.concatenate(
                [ids, np.full((bucket - n, ids.shape[1]), 0, ids.dtype)])
            ids[n:, 0] = BOS_ID
            lens = np.concatenate(
                [lens, np.ones((bucket - n,), lens.dtype)])
        module, params = self._serving_module_params()
        out = np.asarray(greedy_generate(module, params, ids, lens,
                                         max_new))[:n]
        return [self._detok(row) for row in out]

    def _detok(self, ids: Sequence[Any]) -> str:
        """Render generated ids: exact BPE decode when a real tokenizer
        is active, else the learned id→token table (hashing is one-way;
        unknown ids render as ``<id>``)."""
        if self._bpe:
            return self.tokenizer.decode(int(t) for t in ids).lstrip()
        return " ".join(self._id2tok.get(int(t), f"<{int(t)}>")
                        for t in ids)

    def warmup(self) -> None:
        """Compile the serving generate (smallest bucket) before
        traffic arrives."""
        if self._params is None:
            return
        self.predict(["warmup"])

    def make_decode_engine(self, max_slots: int = 8,
                           max_new_tokens: int = 8,
                           steps_per_sync: int = 4,
                           prefill_chunk: int = 32,
                           speculate_k: int = 0,
                           system_prefix: str = "",
                           draft_model: Optional["LlamaLoRA"] = None,
                           kv_page_size: int = 0,
                           kv_pages: int = 0,
                           paged_kernel: Optional[bool] = None,
                           host_kv_pages: int = 0):
        """Continuous-batching serving engine over this model's weights
        (BASELINE.md config #5). The inference worker drives it when
        running in decode-loop mode; see ``serving/decode_engine.py``.

        ``draft_model`` (with ``speculate_k >= 2``): a SMALLER trained
        LlamaLoRA sharing this model's vocabulary drafts the
        speculative continuations instead of prompt-lookup n-grams —
        real draft-model speculation, still greedy-lossless (the
        target's verify step is authoritative either way).

        ``kv_page_size > 0`` serves from a PAGED KV pool of
        ``kv_pages`` pages (block tables; see DecodeEngine): decode-
        cache HBM scales with live tokens and admission backpressures
        on the pool instead of refusing at max_slots × max_len.
        ``kv_pages=0`` defaults to full coverage (no saving, no
        stalls); size it down per docs/operations.md. Token-bit-exact
        with the contiguous engine. The draft model's own cache stays
        contiguous (drafts are small).

        ``paged_kernel`` (paged engines only): ``None`` (auto, the
        default) decodes through the Pallas block-table kernels on TPU
        and the page gather off-TPU; ``True``/``False`` force one
        path. Every decode leg is covered: the s==1 step, chunked
        prefill windows, and speculative-verify windows (the last two
        via ``paged_window_attention``; ``RAFIKI_PAGED_KERNEL_WINDOWS=0``
        drops just the windows back onto the gather). See
        ``ops/paged_attention.py``.

        ``host_kv_pages > 0`` (paged engines only) attaches the
        host-RAM page tier: the admission budget becomes HBM + host
        pages, cold pages spill to pinned host memory and prefetch
        back ahead of the step that resumes them — serviceable
        concurrency stops being hard-capped by HBM (see
        ``serving/kv_tier.py`` and docs/operations.md)."""
        assert self._params is not None, "model is not trained/loaded"
        if host_kv_pages and kv_page_size <= 0:
            raise ValueError("host_kv_pages requires kv_page_size > 0 "
                             "(pages are the host tier's transfer "
                             "unit)")
        if kv_page_size > 0 and not kv_pages:
            kv_pages = _default_kv_pages(max_slots,
                                         int(self.knobs["max_len"]),
                                         int(kv_page_size))
        module, params = self._serving_module_params(
            kv_page_size=kv_page_size, kv_pages=kv_pages,
            paged_kernel=paged_kernel if kv_page_size > 0 else None)
        text_engine = self._build_text_engine(
            module, params, max_slots, max_new_tokens, steps_per_sync,
            prefill_chunk, speculate_k, draft_model=draft_model,
            host_kv_pages=host_kv_pages)
        if system_prefix:
            text_engine.register_prefix(system_prefix)
        return text_engine

    def _build_text_engine(self, module, params, max_slots,
                           max_new_tokens, steps_per_sync, prefill_chunk,
                           speculate_k, draft_model=None,
                           host_kv_pages=0):
        """Common engine wiring for the single- and multi-adapter
        flavors: this model's tokenizer around a DecodeEngine."""
        from rafiki_tpu.serving.decode_engine import (DecodeEngine,
                                                      TextDecodeEngine)

        max_len = int(self.knobs["max_len"])

        def encode(text: str) -> np.ndarray:
            row, n = self.tokenizer.encode(str(text), max_len)
            return row[:max(1, int(n))]

        draft = None
        if draft_model is not None:
            if int(speculate_k) < 2:
                # fail loudly, like the worker's config guard: a caller
                # who handed over a draft believes speculation is live
                raise ValueError(
                    "draft_model requires speculate_k >= 2 "
                    f"(got {speculate_k})")
            assert draft_model._params is not None, \
                "draft model is not trained/loaded"
            d_module, d_params = draft_model._serving_module_params()
            if not _same_tokenizer(self.tokenizer,
                                   draft_model.tokenizer):
                # equal vocab_size is NOT 'same tokenizer': different
                # BPE merge tables map the same ids to different text,
                # so drafts would never match and speculation silently
                # gates off — fail loudly instead
                raise ValueError(
                    "draft and target tokenize differently (merge "
                    "tables / vocab mismatch): speculation compares "
                    "token ids, so the models must share a tokenizer")
            if int(draft_model.knobs["max_len"]) < max_len:
                raise ValueError(
                    "draft max_len must cover the target's (the draft "
                    "cache walks the same positions)")
            # the params must actually fit the draft's knobs: a
            # mis-set draft_knobs would otherwise surface as an opaque
            # XLA shape error at the first dispatch
            abstract = jax.eval_shape(lambda: d_module.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 8), jnp.int32))["params"])
            if not same_tree_shapes(abstract, d_params):
                raise ValueError(
                    "draft parameters do not match the draft model's "
                    "knobs (pass the draft trial's own knobs, e.g. "
                    "the worker config's draft_knobs)")
            draft = (d_module, d_params)
        core = DecodeEngine(module, params,
                            max_slots=max_slots, max_len=max_len,
                            steps_per_sync=steps_per_sync,
                            prefill_chunk=prefill_chunk,
                            speculate_k=speculate_k, draft=draft,
                            host_kv_pages=int(host_kv_pages))
        return TextDecodeEngine(
            core, encode, self._detok,
            max_new=min(max_new_tokens, max_len - 1))

    def make_multi_adapter_engine(self, adapter_params: Sequence[Any],
                                  max_slots: int = 8,
                                  max_new_tokens: int = 8,
                                  steps_per_sync: int = 4,
                                  prefill_chunk: int = 32,
                                  speculate_k: int = 0,
                                  validate: bool = True,
                                  kv_page_size: int = 0,
                                  kv_pages: int = 0,
                                  paged_kernel: Optional[bool] = None,
                                  host_kv_pages: int = 0):
        """ONE continuous-batching engine serving N adapter-only
        fine-tunes of one base (S-LoRA-style multi-adapter serving).

        The reference deploys its best-N trials as N independent worker
        replicas, each holding a full model (SURVEY.md §3.3). When the
        trials are LoRA fine-tunes trained with ``adapters_only=True``,
        they differ only in their (tiny) adapter matrices — so all N
        can share one base model's HBM and one compiled decode step,
        with each request selecting its fine-tune via
        ``submit(..., adapter_id=i)``. Requests against different
        adapters batch together in the same fused step: the base matmul
        runs once for the whole batch; only the rank-r correction is
        per-row (see ``LoRADense.n_adapters``).

        ``adapter_params``: param trees in adapter-id order (e.g.
        ``[trial_a.params, trial_b.params]``); non-adapter leaves must
        be identical across trees (validated unless ``validate=False``)
        and the engine serves with ``adapter_params[0]``'s base.
        Tokenization comes from THIS model. Composes with the
        ``quantize_int8`` knob: the SHARED base kernels quantize once
        (4x less HBM for the one base all N tenants read every step);
        the stacked f32 adapters pass through untouched."""
        trees = list(adapter_params)
        if not trees:
            raise ValueError("adapter_params must name >= 1 trees")
        if host_kv_pages and kv_page_size <= 0:
            raise ValueError("host_kv_pages requires kv_page_size > 0 "
                             "(pages are the host tier's transfer "
                             "unit)")
        stacked = stack_lora_adapters(trees, validate=validate)
        quantized = bool(self.knobs.get("quantize_int8"))
        if quantized:
            stacked = quantize_llama_params(stacked)
        if kv_page_size > 0 and not kv_pages:
            kv_pages = _default_kv_pages(max_slots,
                                         int(self.knobs["max_len"]),
                                         int(kv_page_size))
        module = self._module(quantized=quantized,
                              n_adapters=len(trees),
                              kv_page_size=kv_page_size,
                              kv_pages=kv_pages,
                              paged_kernel=(paged_kernel
                                            if kv_page_size > 0
                                            else None))
        return self._build_text_engine(
            module, stacked, max_slots, max_new_tokens, steps_per_sync,
            prefill_chunk, speculate_k, host_kv_pages=host_kv_pages)

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._params is not None, "model is not trained"
        meta: Dict[str, Any] = {"id2tok": {str(k): v
                                           for k, v in
                                           self._id2tok.items()}}
        if self._bpe:
            # the merge table travels WITH the weights: a serving host
            # can reconstruct the exact tokenizer without the artifact
            # file (tokenizer_path may not exist there)
            meta["bpe_merges"] = [list(m) for m in self.tokenizer.merges]
        return {
            "params": jax.tree_util.tree_map(np.asarray, self._params),
            "meta": meta,
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._id2tok = {int(k): v
                        for k, v in params["meta"]["id2tok"].items()}
        merges = params["meta"].get("bpe_merges")
        if merges is not None:
            from rafiki_tpu.data.bpe import ByteBPETokenizer

            self.tokenizer = ByteBPETokenizer(
                [tuple(int(x) for x in m) for m in merges])
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._qparams = None
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.jsonl"
        val_p = f"{d}/val.jsonl"
        generate_text_classification_dataset(train_p, 192, seed=0)
        generate_text_classification_dataset(val_p, 48, seed=1)
        preds = test_model_class(
            LlamaLoRA, TaskType.LANGUAGE_MODELING, train_p, val_p,
            queries=["tok1 tok2 tok3"],
            knobs={"max_epochs": 6, "vocab_size": 1 << 14, "hidden_dim": 64,
                   "depth": 2, "n_heads": 4, "kv_ratio": 2, "lora_rank": 4,
                   "max_len": 32, "model_parallel": 1,
                   "learning_rate": 1e-2, "batch_size": 16,
                   "quick_train": False, "share_params": False})
        print("continuation:", preds[0])
