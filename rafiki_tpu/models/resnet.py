"""ResNet — the BOHB-search workhorse family (BASELINE.md config #2).

Parity target: the reference zoo's VGG/DenseNet-style TF CNN templates
(SURVEY.md §2 "Model zoo") and benchmark config #2 ("ResNet-50 / ImageNet
with BOHB search across a TPU slice"). TPU-first design notes:

- Convolutions lower straight onto the MXU via XLA; there is no Pallas
  kernel here on purpose — conv+BN+relu is XLA's best-fused path already.
- BatchNorm statistics are **globally correct under data parallelism for
  free**: the batch axis is sharded over the mesh's ``data`` axis and the
  train step is jitted over the mesh, so GSPMD turns the batch-mean
  reductions into cross-device collectives (no hand-written psum, unlike
  torch's SyncBatchNorm).
- Mixed precision: params and BN stats stay f32; compute dtype is bf16 by
  knob (MXU-native).
- Small-image inputs (CIFAR/FashionMNIST-scale) get a 3x3/stride-1 stem
  with no max-pool; ImageNet-scale inputs the classic 7x7/stride-2 stem.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_image_classification_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, KnobConfig, PolicyKnob,
                              TrainContext, bucketed_forward, conform_images,
                              same_tree_shapes, train_epoch)
from rafiki_tpu.parallel.sharding import (batch_sharding, make_mesh,
                                          replicated)

#: variant name -> (stage sizes, use bottleneck blocks)
VARIANTS: Dict[str, Tuple[Tuple[int, ...], bool]] = {
    "resnet18": ((2, 2, 2, 2), False),
    "resnet34": ((3, 4, 6, 3), False),
    "resnet50": ((3, 4, 6, 3), True),
    "resnet101": ((3, 4, 23, 3), True),
}


class _Block(nn.Module):
    """Basic residual block: 3x3 conv ×2."""

    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        # zero-init final BN scale: residual branch starts as identity
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="shortcut")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class _Bottleneck(nn.Module):
    """Bottleneck residual block: 1x1 → 3x3 → 1x1 (4× expansion)."""

    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        # stride on the 3x3 (the "v1.5" placement — better accuracy than
        # striding the first 1x1)
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides),
                            name="shortcut")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet over (B, H, W, C) images.

    ``resnet50`` = stage_sizes (3,4,6,3) with bottleneck=True, width=64.
    """

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    n_classes: int = 1000
    small_inputs: bool = False  # CIFAR-style stem
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = nn.Conv(self.width, (3, 3), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
        else:
            x = nn.Conv(self.width, (7, 7), (2, 2), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        block: Callable[..., Any] = _Bottleneck if self.bottleneck else _Block
        for i, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2 ** i)
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block(filters, strides, self.dtype,
                          name=f"stage{i}_block{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.n_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


class ResNetClassifier(BaseModel):
    """ResNet template: image classification, DP over the trial sub-mesh,
    SGD-momentum with cosine decay (the classic recipe)."""

    TASKS = (TaskType.IMAGE_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "variant": CategoricalKnob(list(VARIANTS),
                                       shape_relevant=True),
            "width_mult": CategoricalKnob([0.25, 0.5, 1.0],
                                          shape_relevant=True),
            "learning_rate": FloatKnob(1e-3, 1.0, is_exp=True),
            "weight_decay": FloatKnob(1e-5, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128, 256],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._vars: Optional[Dict[str, Any]] = None
        self._n_classes: Optional[int] = None
        self._image_shape: Optional[Sequence[int]] = None
        self._fwd: Optional[Any] = None  # cached jitted forward

    # ---- internals ----
    def _module(self) -> ResNet:
        assert self._n_classes is not None and self._image_shape is not None
        stages, bottleneck = VARIANTS[str(self.knobs["variant"])]
        width = max(8, int(64 * float(self.knobs["width_mult"])))
        small = min(self._image_shape[0], self._image_shape[1]) < 64
        dtype = jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32
        return ResNet(stage_sizes=stages, bottleneck=bottleneck, width=width,
                      n_classes=int(self._n_classes), small_inputs=small,
                      dtype=dtype)

    def _prep(self, images: np.ndarray) -> np.ndarray:
        x = images.astype(np.float32) / 255.0
        if x.ndim == 3:
            x = x[..., None]
        # global average pooling makes the net resolution-agnostic, but the
        # stem conv's input channel count is fixed at train time
        return conform_images(x, self._image_shape)

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = load_image_classification_dataset(dataset_path)
        self._n_classes = ds.n_classes
        self._image_shape = ds.image_shape
        x = self._prep(ds.images)
        y = ds.labels

        module = self._module()
        devices = ctx.devices or jax.local_devices()
        mesh = make_mesh(devices)
        b_shard = batch_sharding(mesh)
        r_shard = replicated(mesh)

        n_data = len(devices)
        batch_size = int(self.knobs["batch_size"])
        batch_size = max(n_data, batch_size - batch_size % n_data)

        if self._vars is None:
            variables = module.init(jax.random.PRNGKey(0),
                                    jnp.zeros((1, *x.shape[1:])), train=False)
            variables = {"params": variables["params"],
                         "batch_stats": variables["batch_stats"]}
        else:
            variables = self._vars
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(variables["params"],
                                                       shared):
                variables = {
                    "params": jax.tree_util.tree_map(jnp.asarray, shared),
                    "batch_stats": jax.tree_util.tree_map(
                        jnp.asarray,
                        ctx.shared_params.get("batch_stats",
                                              variables["batch_stats"])),
                }

        epochs = max(1, round(int(self.knobs["max_epochs"])
                              * float(ctx.budget_scale)))
        if self.knobs.get("quick_train"):
            epochs = min(epochs, 2)
        steps_per_epoch = max(1, (len(x) + batch_size - 1) // batch_size)
        schedule = optax.cosine_decay_schedule(
            float(self.knobs["learning_rate"]), epochs * steps_per_epoch)

        def decay_mask(tree):
            # classic recipe: no decay on biases or BatchNorm scale/bias
            return jax.tree_util.tree_map_with_path(
                lambda kp, _: str(getattr(kp[-1], "key", "")) not in
                ("bias", "scale"), tree)

        tx = optax.chain(
            optax.add_decayed_weights(float(self.knobs["weight_decay"]),
                                      mask=decay_mask),
            optax.sgd(schedule, momentum=0.9, nesterov=True))

        params = jax.device_put(variables["params"], r_shard)
        batch_stats = jax.device_put(variables["batch_stats"], r_shard)
        opt_state = jax.device_put(tx.init(params), r_shard)

        # donate the param/stats/opt trees: in-place update, no per-step
        # copies riding HBM bandwidth
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(params, batch_stats, opt_state, xb, yb, mask):
            def loss_fn(p):
                logits, updates = module.apply(
                    {"params": p, "batch_stats": batch_stats}, xb,
                    train=True, mutable=["batch_stats"])
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb)
                loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask),
                                                            1.0)
                return loss, updates["batch_stats"]

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_stats,
                    opt_state, loss)

        def step(state, b):
            params, batch_stats, opt_state = state
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, b["x"], b["y"], b["m"])
            return (params, batch_stats, opt_state), loss

        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._vars (warm
        # start / re-train): drop the stale reference first
        self._vars = None
        with mesh:
            for epoch in range(epochs):
                state = (params, batch_stats, opt_state)
                (params, batch_stats, opt_state), mean_loss = train_epoch(
                    step, state,
                    ({"x": b["x"], "y": b["y"],
                      "m": b["mask"].astype(np.float32)}
                     for b in batch_iterator({"x": x, "y": y}, batch_size,
                                             seed=epoch)),
                    sharding=b_shard)
                ctx.logger.log(epoch=epoch, loss=mean_loss)
                if ctx.checkpoint is not None:
                    # preemption safety: worker throttles + persists
                    self._vars = {"params": params, "batch_stats": batch_stats}
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs)
                if ctx.should_continue is not None and \
                        not ctx.should_continue(epoch, -mean_loss):
                    break
        self._vars = {"params": params, "batch_stats": batch_stats}
        self._fwd = None  # new params/arch → rebuild the cached jit

    def evaluate(self, dataset_path: str) -> float:
        ds = load_image_classification_dataset(dataset_path)
        probs = self._predict_probs(self._prep(ds.images))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = self._prep(np.stack([np.asarray(q) for q in queries]))
        return [p.tolist() for p in self._predict_probs(x)]

    def _predict_probs(self, x: np.ndarray) -> np.ndarray:
        assert self._vars is not None, "model is not trained/loaded"
        if self._fwd is None:  # cache: jit memoizes by function identity
            module = self._module()

            @jax.jit
            def forward(variables, xb):
                logits = module.apply(variables, xb, train=False)
                return jax.nn.softmax(logits.astype(jnp.float32), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._vars, x, bucket=64)

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._vars is not None, "model is not trained"
        return {
            "params": jax.tree_util.tree_map(np.asarray,
                                             self._vars["params"]),
            "batch_stats": jax.tree_util.tree_map(
                np.asarray, self._vars["batch_stats"]),
            "meta": {"n_classes": self._n_classes,
                     "image_shape": list(self._image_shape or [])},
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._image_shape = list(params["meta"]["image_shape"])
        self._vars = {
            "params": jax.tree_util.tree_map(jnp.asarray, params["params"]),
            "batch_stats": jax.tree_util.tree_map(jnp.asarray,
                                                  params["batch_stats"]),
        }
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 256, seed=0)
        ds = generate_image_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            ResNetClassifier, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
            queries=[ds.images[0]],
            knobs={"variant": "resnet18", "width_mult": 0.25,
                   "batch_size": 32, "max_epochs": 5, "learning_rate": 0.1,
                   "weight_decay": 1e-4, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
