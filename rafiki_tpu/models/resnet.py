"""ResNet — the BOHB-search workhorse family (BASELINE.md config #2).

Parity target: the reference zoo's VGG/DenseNet-style TF CNN templates
(SURVEY.md §2 "Model zoo") and benchmark config #2 ("ResNet-50 / ImageNet
with BOHB search across a TPU slice"). TPU-first design notes:

- Convolutions lower straight onto the MXU via XLA; there is no Pallas
  kernel here on purpose — conv+BN+relu is XLA's best-fused path already.
- BatchNorm statistics are **globally correct under data parallelism for
  free**: the batch axis is sharded over the mesh's ``data`` axis and the
  train step is jitted over the mesh, so GSPMD turns the batch-mean
  reductions into cross-device collectives (no hand-written psum, unlike
  torch's SyncBatchNorm).
- Mixed precision: params and BN stats stay f32; compute dtype is bf16 by
  knob (MXU-native).
- Small-image inputs (CIFAR/FashionMNIST-scale) get a 3x3/stride-1 stem
  with no max-pool; ImageNet-scale inputs the classic 7x7/stride-2 stem.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import (CategoricalKnob, FixedKnob, FloatKnob,
                              KnobConfig, PolicyKnob)
from rafiki_tpu.models._cnn_base import BatchNormCNNTemplate

#: variant name -> (stage sizes, use bottleneck blocks)
VARIANTS: Dict[str, Tuple[Tuple[int, ...], bool]] = {
    "resnet18": ((2, 2, 2, 2), False),
    "resnet34": ((3, 4, 6, 3), False),
    "resnet50": ((3, 4, 6, 3), True),
    "resnet101": ((3, 4, 23, 3), True),
}


class _Block(nn.Module):
    """Basic residual block: 3x3 conv ×2."""

    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        # zero-init final BN scale: residual branch starts as identity
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides),
                            name="shortcut")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class _Bottleneck(nn.Module):
    """Bottleneck residual block: 1x1 → 3x3 → 1x1 (4× expansion)."""

    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        # stride on the 3x3 (the "v1.5" placement — better accuracy than
        # striding the first 1x1)
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides),
                            name="shortcut")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet over (B, H, W, C) images.

    ``resnet50`` = stage_sizes (3,4,6,3) with bottleneck=True, width=64.
    """

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    width: int = 64
    n_classes: int = 1000
    small_inputs: bool = False  # CIFAR-style stem
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = nn.Conv(self.width, (3, 3), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
        else:
            x = nn.Conv(self.width, (7, 7), (2, 2), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        block: Callable[..., Any] = _Bottleneck if self.bottleneck else _Block
        for i, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2 ** i)
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block(filters, strides, self.dtype,
                          name=f"stage{i}_block{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.n_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


class ResNetClassifier(BatchNormCNNTemplate):
    """ResNet template: image classification, DP over the trial sub-mesh,
    SGD-momentum with cosine decay (shared BatchNorm-CNN recipe —
    ``models/_cnn_base.py``)."""

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "variant": CategoricalKnob(list(VARIANTS),
                                       shape_relevant=True),
            "width_mult": CategoricalKnob([0.25, 0.5, 1.0],
                                          shape_relevant=True),
            # traceable: continuous optimizer knobs are gang-lane-ready
            # (they never fork the compiled program); the BatchNorm CNN
            # recipe still trains per-trial until a gang spec lands, but
            # the trial scheduler already buckets on the structural
            # knobs only
            "learning_rate": FloatKnob(1e-3, 1.0, is_exp=True,
                                       traceable=True),
            "weight_decay": FloatKnob(1e-5, 1e-2, is_exp=True,
                                      traceable=True),
            "batch_size": CategoricalKnob([32, 64, 128, 256],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def _module(self) -> ResNet:
        assert self._n_classes is not None and self._image_shape is not None
        stages, bottleneck = VARIANTS[str(self.knobs["variant"])]
        width = max(8, int(64 * float(self.knobs["width_mult"])))
        small = min(self._image_shape[0], self._image_shape[1]) < 64
        dtype = jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32
        return ResNet(stage_sizes=stages, bottleneck=bottleneck, width=width,
                      n_classes=int(self._n_classes), small_inputs=small,
                      dtype=dtype)


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 256, seed=0)
        ds = generate_image_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            ResNetClassifier, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
            queries=[ds.images[0]],
            knobs={"variant": "resnet18", "width_mult": 0.25,
                   "batch_size": 32, "max_epochs": 5, "learning_rate": 0.1,
                   "weight_decay": 1e-4, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
