"""JaxFeedForward — the ``TfFeedForward``-equivalent template (config #1).

Parity target: the reference zoo's ``TfFeedForward`` FashionMNIST template
(SURVEY.md §2 "Model zoo", §6 config 1): a small dense net for image
classification with knobs over depth/width/lr/batch size. Rebuilt as a
flax.linen module with a fully ``jax.jit``-compiled train step (donated
optimizer state, static batch shapes) so the same code path runs CPU or a
TPU sub-mesh unchanged.

Knob application is *functional*: the train step is a pure function over
an explicit ``{"params", "opt"}`` state with the traceable knob
(``learning_rate``) arriving as a traced scalar operand — the SAME
functions back the sequential ``train()`` loop and the gang-compiled
tuning engine's vmapped lanes (``make_gang_spec``), so a 1-lane gang
trial reproduces a sequential trial bit-for-bit (tier-1 asserts it).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

# NOTE: zoo templates use absolute imports — their module source is shipped
# to workers via serialize_model_class() and re-imported standalone, where
# relative imports have no parent package.
from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_image_classification_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, GangSpec, IntegerKnob, KnobConfig,
                              Knobs, PolicyKnob, TrainContext,
                              bucketed_forward, conform_images,
                              same_tree_shapes)


class _MLP(nn.Module):
    hidden_layer_count: int
    hidden_layer_units: int
    n_classes: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))
        for _ in range(self.hidden_layer_count):
            x = nn.Dense(self.hidden_layer_units)(x)
            x = nn.relu(x)
        return nn.Dense(self.n_classes)(x)


class JaxFeedForward(BaseModel):
    """Dense image classifier (FashionMNIST-class workloads)."""

    TASKS = (TaskType.IMAGE_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "hidden_layer_count": IntegerKnob(1, 3, shape_relevant=True),
            "hidden_layer_units": IntegerKnob(16, 256, is_exp=True,
                                              shape_relevant=True),
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True,
                                       traceable=True),
            "batch_size": CategoricalKnob([32, 64, 128],
                                          shape_relevant=True),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._n_classes: Optional[int] = None
        self._image_shape: Optional[Sequence[int]] = None
        self._fwd: Optional[Any] = None  # cached jitted forward

    # ---- internals ----
    def _module(self) -> _MLP:
        assert self._n_classes is not None
        return _MLP(hidden_layer_count=int(self.knobs["hidden_layer_count"]),
                    hidden_layer_units=int(self.knobs["hidden_layer_units"]),
                    n_classes=self._n_classes)

    @staticmethod
    def _to_float(images: np.ndarray) -> np.ndarray:
        return images.astype(np.float32) / 255.0

    @staticmethod
    def _lane_functions(module: "_MLP", sample_shape: Sequence[int]):
        """``(init_lane, train_step)`` — the functional training core
        shared by the sequential ``train()`` loop and the gang engine's
        vmapped lanes (1 lane == 1 sequential trial, bit-for-bit).

        ``hp`` carries the traceable knobs as traced scalars:
        ``optax.adam(lr)`` is exactly ``scale_by_adam`` followed by
        ``scale(-lr)``, so applying ``-lr`` to the adam-scaled updates
        keeps the math identical while letting lr differ per lane
        inside one compiled program."""
        tx = optax.scale_by_adam()

        def init_lane(rng: Any, hp: Dict[str, Any]) -> Dict[str, Any]:
            params = module.init(rng,
                                 jnp.zeros((1, *sample_shape)))["params"]
            return {"params": params, "opt": tx.init(params)}

        def train_step(state: Dict[str, Any], hp: Dict[str, Any],
                       batch: Dict[str, Any]):
            def loss_fn(p):
                logits = module.apply({"params": p}, batch["x"])
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, batch["y"])
                mask = batch["mask"].astype(jnp.float32)
                return jnp.sum(losses * mask) / jnp.maximum(
                    jnp.sum(mask), 1)

            loss, grads = jax.value_and_grad(loss_fn)(state["params"])
            updates, opt = tx.update(grads, state["opt"], state["params"])
            updates = jax.tree_util.tree_map(
                lambda u: -hp["learning_rate"] * u, updates)
            return {"params": optax.apply_updates(state["params"], updates),
                    "opt": opt}, loss

        return init_lane, train_step

    @classmethod
    def gang_epochs(cls, knobs: Knobs, budget_scale: float) -> int:
        """Epoch count ``train()`` would spend — the gang scheduler's
        per-lane budget (must mirror the sequential loop exactly)."""
        return max(1, round(int(knobs["max_epochs"]) * float(budget_scale)))

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = load_image_classification_dataset(dataset_path)
        self._n_classes = ds.n_classes
        self._image_shape = ds.image_shape
        x = self._to_float(ds.images)
        y = ds.labels

        module = self._module()
        batch_size = int(self.knobs["batch_size"])
        init_lane, train_step = self._lane_functions(module, x.shape[1:])
        hp = {"learning_rate":
              jnp.float32(float(self.knobs["learning_rate"]))}
        state = init_lane(jax.random.PRNGKey(0), hp)
        if self._params is not None:  # warm-started via load_parameters
            state = {"params": self._params, "opt": state["opt"]}
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(state["params"],
                                                       shared):
                state = {"params": jax.tree_util.tree_map(jnp.asarray,
                                                          shared),
                         "opt": state["opt"]}
            # else: incompatible architecture → cold start

        # donate the state tree: in-place update, no per-step copies
        step = jax.jit(train_step, donate_argnums=(0,))
        epochs = self.gang_epochs(self.knobs, ctx.budget_scale)
        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._params (warm
        # start / re-train): drop the stale reference first
        self._params = None
        for epoch in range(epochs):
            losses = []
            for batch in batch_iterator({"x": x, "y": y}, batch_size,
                                        seed=epoch):
                state, loss = step(state, hp, batch)
                losses.append(float(loss))
            mean_loss = float(np.mean(losses))
            ctx.logger.log(epoch=epoch, loss=mean_loss)
            if ctx.checkpoint is not None:
                # preemption safety: worker throttles + persists
                self._params = state["params"]
                ctx.checkpoint(self.dump_parameters,
                               frac_done=(epoch + 1) / epochs)
            if ctx.should_continue is not None and \
                    not ctx.should_continue(epoch, -mean_loss):
                break
        self._params = state["params"]
        self._fwd = None  # new params/arch → rebuild the cached jit

    @classmethod
    def make_gang_spec(cls, knobs: Knobs, train_dataset_path: str,
                       val_dataset_path: str) -> GangSpec:
        """Functional training recipe for the gang-compiled tuning
        engine: everything but ``learning_rate`` (the traceable knob) is
        burned in from ``knobs`` — proposals sharing this static bucket
        train as lanes of one vmapped step."""
        ds = load_image_classification_dataset(train_dataset_path)
        x = cls._to_float(ds.images)
        y = ds.labels
        module = _MLP(hidden_layer_count=int(knobs["hidden_layer_count"]),
                      hidden_layer_units=int(knobs["hidden_layer_units"]),
                      n_classes=ds.n_classes)
        batch_size = int(knobs["batch_size"])
        init_lane, train_step = cls._lane_functions(module, x.shape[1:])
        vds = load_image_classification_dataset(val_dataset_path)
        vx = conform_images(cls._to_float(vds.images), ds.image_shape)
        vy = vds.labels
        meta = {"n_classes": ds.n_classes,
                "image_shape": list(ds.image_shape)}

        def epoch_batches(epoch: int):
            return batch_iterator({"x": x, "y": y}, batch_size, seed=epoch)

        def eval_lane(state, hp, xb):
            # argmax(logits) == argmax(softmax(logits)) — matches
            # evaluate()'s accuracy exactly
            return jnp.argmax(module.apply({"params": state["params"]},
                                           xb), -1)

        def eval_batches():
            return batch_iterator({"x": vx, "y": vy}, 256, shuffle=False)

        def export_blob(lane_state, hp):
            return {"params": jax.tree_util.tree_map(
                        np.asarray, lane_state["params"]),
                    "meta": dict(meta)}

        def warm_lane(fresh, blob):
            shared = (blob or {}).get("params")
            if shared is None or not same_tree_shapes(fresh["params"],
                                                      shared):
                return fresh  # incompatible architecture → cold start
            return {"params": jax.tree_util.tree_map(jnp.asarray, shared),
                    "opt": fresh["opt"]}

        return GangSpec(hp_names=("learning_rate",), init_lane=init_lane,
                        train_step=train_step, epoch_batches=epoch_batches,
                        eval_lane=eval_lane, eval_batches=eval_batches,
                        export_blob=export_blob, warm_lane=warm_lane,
                        share_params_knob="share_params")

    def evaluate(self, dataset_path: str) -> float:
        ds = load_image_classification_dataset(dataset_path)
        x = conform_images(self._to_float(ds.images), self._image_shape)
        probs = self._predict_probs(x)
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = self._to_float(np.stack([np.asarray(q) for q in queries]))
        if x.ndim == 3:
            x = x[..., None]
        # the flatten→Dense input width is fixed at train time
        x = conform_images(x, self._image_shape)
        return [p.tolist() for p in self._predict_probs(x)]

    def _predict_probs(self, x: np.ndarray) -> np.ndarray:
        assert self._params is not None, "model is not trained/loaded"
        if self._fwd is None:  # cache: jit memoizes by function identity
            module = self._module()

            @jax.jit
            def forward(params, xb):
                return jax.nn.softmax(
                    module.apply({"params": params}, xb), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._params, x, bucket=256)

    def warmup(self) -> None:
        """Compile the serving forward before traffic arrives."""
        if self._params is None or self._image_shape is None:
            return
        self.predict([np.zeros(list(self._image_shape), np.uint8)])

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._params is not None, "model is not trained"
        return {
            "params": jax.tree_util.tree_map(np.asarray, self._params),
            "meta": {"n_classes": self._n_classes,
                     "image_shape": list(self._image_shape or [])},
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._image_shape = list(params["meta"]["image_shape"])
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 512, seed=0)
        ds = generate_image_classification_dataset(val_p, 128, seed=1)
        preds = test_model_class(
            JaxFeedForward, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
            queries=[ds.images[0], ds.images[1]])
        print("predictions:", [int(np.argmax(p)) for p in preds])
